"""BERT-base per-op roofline on the real chip (VERDICT r5 item 2a).

ResNet got the measured-ceiling treatment in round 4
(tools/resnet_mfu_analysis.md); this does the same for the headline BERT
workload: where do the points between the measured train MFU and the
chip's ~66% matmul ceiling go?

Methodology (same as the ResNet tool): every number comes from a
scan-chained loop on the device (data dependence through the carry so XLA
cannot hoist the body), timed around a single D2H read; the shared-tunnel
dispatch RTT amortizes to <2% over 100+ iterations.

Stages:
  1. GEMM ceilings at BERT-base's exact shapes (qkv/proj/mlp/vocab-head).
  2. One encoder layer forward / fwd+bwd, then ablations that remove one
     bandwidth suspect at a time (softmax path, dropout, LayerNorm) —
     the deltas localize the gap.
  3. Full-model forward, full train step, optimizer-only step — the
     residue (embedding scatter, MLM gather, AdamW passes) falls out.

Run:  python tools/bert_mfu_roofline.py          (ambient TPU)
Output: one JSON line per measurement + a closing summary line.
"""
import json
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

B, S, D, H, FF, V = 256, 128, 768, 12, 3072, 30522
DH = D // H
PEAK_TFLOPS = 197.0  # v5e bf16


def _timed_chain(fn, x0, iters, *consts):
    """Run ``x = fn(x, *consts)`` ``iters`` times under one jit with a real
    data dependence, return seconds for the whole chain."""
    import jax
    from jax import lax

    @jax.jit
    def chain(x, *consts):
        def body(x, _):
            return fn(x, *consts), None

        out, _ = lax.scan(body, x, None, length=iters)
        return out

    out = chain(x0, *consts)
    _sync(out)
    t0 = time.perf_counter()
    out = chain(x0, *consts)
    _sync(out)
    return time.perf_counter() - t0


def _sync(tree):
    import jax

    leaf = jax.tree_util.tree_leaves(tree)[0]
    float(np.asarray(leaf.reshape(-1)[0]))  # D2H read truly waits


def emit(name, ms, gflop=None, note=""):
    rec = {"op": name, "ms": round(ms, 3)}
    if gflop is not None:
        tf = gflop / ms  # GFLOP / ms == TFLOP/s
        rec["tflops"] = round(tf, 1)
        rec["mfu_pct"] = round(100 * tf / PEAK_TFLOPS, 1)
    if note:
        rec["note"] = note
    print(json.dumps(rec), flush=True)
    return rec


def stage1_gemms():
    import jax
    import jax.numpy as jnp

    shapes = [
        ("qkv  [BS,D]x[D,3D]", (B * S, D, 3 * D)),
        ("proj [BS,D]x[D,D]", (B * S, D, D)),
        ("mlp1 [BS,D]x[D,4D]", (B * S, D, FF)),
        ("mlp2 [BS,4D]x[4D,D]", (B * S, FF, D)),
        ("head [B*20,D]x[D,V]", (B * 20, D, V)),
        ("attn scores [S,DH]x[DH,S] batched BH",
         (S, DH, S)),  # per-(B,H) GEMM, batched below
    ]
    out = {}
    for name, (m, k, n) in shapes:
        batch = B * H if name.startswith("attn") else 1
        key = jax.random.PRNGKey(0)
        if batch > 1:
            a = jax.random.normal(key, (batch, m, k), jnp.bfloat16)
            w = jax.random.normal(key, (batch, k, n), jnp.bfloat16)
            fn = lambda x, w: jnp.einsum("bmk,bkn->bmn", x, w)  # noqa: E731
        else:
            a = jax.random.normal(key, (m, k), jnp.bfloat16)
            w = jax.random.normal(key, (k, n), jnp.bfloat16)
            fn = lambda x, w: (x @ w).astype(jnp.bfloat16)  # noqa: E731

        iters = 100
        # keep the carry shape == input shape: project back when n != k
        if m * n != m * k or batch > 1:
            proj = (jax.random.normal(key, (batch, n, k), jnp.bfloat16)
                    if batch > 1 else
                    jax.random.normal(key, (n, k), jnp.bfloat16))
            if batch > 1:
                f2 = lambda x, w, p: jnp.einsum(  # noqa: E731
                    "bmn,bnk->bmk", fn(x, w), p).astype(jnp.bfloat16)
            else:
                f2 = lambda x, w, p: (fn(x, w) @ p).astype(  # noqa: E731
                    jnp.bfloat16)
            sec = _timed_chain(f2, a, iters, w, proj)
            gflop = 2 * batch * m * k * n * 2 * iters / 1e9  # x2: the proj
        else:
            sec = _timed_chain(fn, a, iters, w)
            gflop = 2 * batch * m * k * n * iters / 1e9
        out[name] = emit(f"gemm {name}", sec * 1e3 / iters,
                         gflop / iters)
    return out


def _make_layer(dropout, attention="full", layernorm=True):
    """One BERT encoder layer as a pure function of (x, params)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertConfig, BertLayer
    from paddle_tpu.nn.layer_base import functional_call

    paddle.seed(0)
    cfg = BertConfig(vocab_size=V, hidden_size=D, num_layers=1,
                     num_heads=H, intermediate_size=FF,
                     dropout=dropout)
    layer = BertLayer(cfg).astype("bfloat16")
    params = {k: v.value for k, v in layer.named_parameters()}

    if attention == "gemm_only":
        # replace softmax-path with a pure GEMM chain of the same matmul
        # FLOPs: qkv → (q@k^T)@v without softmax/mask/scale
        def attn_fwd(self, x, attn_mask=None):
            Bx, Sx, Dx = x.shape
            qkv = self.qkv(x).reshape(Bx, Sx, 3, H, DH)
            q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k)
            ctx = jnp.einsum("bhqk,bhkd->bhqd", scores.astype(q.dtype), v)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(Bx, Sx, Dx)
            return self.out(ctx)

        layer.attn.forward = attn_fwd.__get__(layer.attn)
    elif attention == "flash":
        from paddle_tpu.ops.flash_attention import flash_attention

        def attn_fwd(self, x, attn_mask=None):
            Bx, Sx, Dx = x.shape
            qkv = self.qkv(x).reshape(Bx, Sx, 3, H, DH)
            # kernel layout: [B, H, S, DH]
            q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
            ctx = flash_attention(q, k, v, causal=False)
            return self.out(ctx.transpose(0, 2, 1, 3).reshape(Bx, Sx, Dx))

        layer.attn.forward = attn_fwd.__get__(layer.attn)

    if not layernorm:
        for name in ("ln1", "ln2"):
            ln = getattr(layer, name)
            ln.forward = (lambda self, x: x).__get__(ln)

    def fwd(x, params, key):
        return functional_call(layer, params, x, rngs=key,
                               training=True).astype(jnp.bfloat16)

    return fwd, params


LAYER_GEMM_GFLOP = 2 * B * S * (3 * D * D + D * D + 2 * D * FF) / 1e9
ATTN_GEMM_GFLOP = 4 * B * H * S * S * DH / 1e9


def stage2_layer():
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(1)
    x0 = jax.random.normal(key, (B, S, D), jnp.bfloat16)
    results = {}
    variants = [
        ("layer fwd (full, p=0.1)", dict(dropout=0.1)),
        ("layer fwd (no dropout)", dict(dropout=0.0)),
        ("layer fwd (gemm-only attn)", dict(dropout=0.0,
                                            attention="gemm_only")),
        ("layer fwd (no layernorm)", dict(dropout=0.0, layernorm=False)),
        ("layer fwd (flash attn)", dict(dropout=0.0, attention="flash")),
    ]
    for name, kw in variants:
        try:
            fwd, params = _make_layer(**kw)
            iters = 50
            sec = _timed_chain(lambda x, p: fwd(x, p, jax.random.PRNGKey(2)),
                               x0, iters, params)
            gf = (LAYER_GEMM_GFLOP + ATTN_GEMM_GFLOP) * iters
            results[name] = emit(name, sec * 1e3 / iters, gf / iters)
        except Exception as e:  # flash variant may not support the shape
            print(json.dumps({"op": name, "error": str(e)[:200]}),
                  flush=True)

    # fwd+bwd on the full layer
    fwd, params = _make_layer(dropout=0.1)

    def train_like(x, params):
        import jax

        loss, grads = jax.value_and_grad(
            lambda p: fwd(x, p, jax.random.PRNGKey(2)).astype(
                jnp.float32).mean())(params)
        # fold a grad signal back into x so the chain carries dependence
        gleaf = jax.tree_util.tree_leaves(grads)[0]
        return (x + gleaf.reshape(-1)[0].astype(x.dtype) * 1e-12).astype(x.dtype)

    iters = 30
    sec = _timed_chain(train_like, x0, iters, params)
    gf = 3 * (LAYER_GEMM_GFLOP + ATTN_GEMM_GFLOP) * iters
    results["layer fwd+bwd"] = emit("layer fwd+bwd (p=0.1)",
                                    sec * 1e3 / iters, gf / iters)
    return results


def stage3_model():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import optimizer as popt
    from paddle_tpu.models import BertForPretraining, bert_base

    paddle.seed(0)
    cfg = bert_base()
    net = BertForPretraining(cfg).astype("bfloat16")
    opt = popt.AdamW(learning_rate=1e-4, weight_decay=0.01,
                     multi_precision=True)
    model = paddle.Model(
        net, inputs=["input_ids", "token_type_ids", "attention_mask",
                     "masked_positions"],
        labels=["mlm_labels", "nsp_labels"])
    model.prepare(optimizer=opt, loss=net.loss)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    tt = (rng.uniform(size=(B, S)) < 0.5).astype(np.int32)
    am = np.ones((B, S), np.int32)
    pos = np.stack([np.sort(rng.choice(S, 20, replace=False))
                    for _ in range(B)]).astype(np.int32)
    mlm = np.take_along_axis(ids, pos, axis=1)
    nsp = rng.randint(0, 2, (B, 1)).astype(np.int32)

    def step():
        loss, _ = model._train_batch_device([ids, tt, am, pos], [mlm, nsp])
        return loss

    for _ in range(3):
        loss = step()
    float(np.asarray(loss))
    t0 = time.perf_counter()
    for _ in range(10):
        loss = step()
    float(np.asarray(loss))
    sec = (time.perf_counter() - t0) / 10
    from bench import BERT_TRAIN_GFLOP_PER_SEQ  # single source of truth

    emit("full train step", sec * 1e3, B * BERT_TRAIN_GFLOP_PER_SEQ,
         note=f"{B / sec:.0f} seq/s")
    return sec


def main():
    import jax

    print(json.dumps({"devices": [str(d) for d in jax.devices()]}),
          flush=True)
    g = stage1_gemms()
    l = stage2_layer()
    stage3_model()
    print(json.dumps({"summary": "see per-line records", "B": B, "S": S}),
          flush=True)


if __name__ == "__main__":
    main()

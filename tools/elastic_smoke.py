"""Elastic-training gate: divergence rollback, exact resume, watchdog (CPU).

One-command proof of the training supervisor's contracts, run on every
gate pass:

1. **NaN rollback** — a supervised train loop with one injected NaN loss
   must trip exactly ONE rollback, skip the poison batch, and complete
   with finite losses (rule F802 stays silent on this clean path).
2. **SIGKILL mid-epoch → exact resume** — a child trainer checkpointing
   through ``AutoCheckpoint(data_loader=...)`` is SIGKILLed mid-epoch;
   rerunning it resumes and must produce final params BIT-IDENTICAL to
   an uninterrupted run (the "batches are replayed" caveat is gone).
3. **Wedged collective** — with ``FLAGS_collective_timeout_s`` armed and
   a latency fault at the ``collective.call`` site, the all-reduce must
   raise ``TransientDeviceError`` within the deadline instead of hanging.
4. **Rollback loop → F802** — a run whose every step diverges must die
   with ``DivergenceError`` after the per-target budget, and analysis
   rule F802 must fire on the RetraceMonitor that watched it.
5. **Disabled hooks** — with the supervisor disabled and the watchdog
   flag at 0.0, the guarded loop is bit-identical to a bare one and no
   baseline checkpoint is committed.

Prints one JSON line; exit 0 iff every gate holds.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _model(seed=0):
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as popt

    pt.seed(seed)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    model = pt.Model(net, inputs=["x"], labels=["y"])
    model.prepare(optimizer=popt.Adam(learning_rate=1e-2),
                  loss=nn.CrossEntropyLoss())
    return model


def _loader():
    import numpy as np

    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.dataset import TensorDataset

    rng = np.random.RandomState(0)
    x = rng.randn(32, 4).astype(np.float32)
    y = rng.randint(0, 2, size=(32,)).astype(np.int64)
    return DataLoader(TensorDataset([x, y]), batch_size=4, shuffle=True,
                      return_numpy=True)


def _committed(ckpt_dir):
    from paddle_tpu.incubate.checkpoint import _META, _PREFIX

    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(n for n in os.listdir(ckpt_dir)
                  if n.startswith(_PREFIX)
                  and os.path.exists(os.path.join(ckpt_dir, n, _META)))


def elastic_child(ckpt_dir, out_path):
    """Subprocess body: 3 supervised epochs over a shuffled exact-resume
    loader, checkpointing every 3 steps; dumps final params and exits 0.
    The parent may SIGKILL us mid-epoch — rerunning resumes exactly."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.incubate.checkpoint import AutoCheckpoint

    pt.seed(77)
    loader = _loader()
    model = _model(seed=1)
    acp = AutoCheckpoint(model, ckpt_dir, save_steps=3, async_save=False,
                         data_loader=loader)
    acp.resume()
    for epoch in range(acp.last_epoch, 3):
        for x, y in loader:
            model.train_batch([x], [y])
            acp.step(epoch)
            time.sleep(0.04)  # widen the parent's mid-epoch kill window
        acp.epoch_end(epoch)
    acp.close()
    np.savez(out_path,
             **{k: np.asarray(v)
                for k, v in model.network.state_dict().items()})
    return 0


def _run_child(ckpt_dir, out_path, kill_after_commits=None):
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--elastic-child",
         ckpt_dir, out_path],
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    if kill_after_commits is None:
        child.wait()
        return child.returncode
    deadline = time.time() + 120
    try:
        while len(_committed(ckpt_dir)) < kill_after_commits:
            if child.poll() is not None:
                return child.returncode  # finished before the kill window
            if time.time() > deadline:
                return -999
            time.sleep(0.02)
    finally:
        if child.poll() is None:
            child.send_signal(signal.SIGKILL)
        child.wait()
    return -signal.SIGKILL


def gate_sigkill_exact_resume(tmp):
    import numpy as np

    ref_out = os.path.join(tmp, "ref.npz")
    rc = _run_child(os.path.join(tmp, "ck-ref"), ref_out)
    if rc != 0 or not os.path.exists(ref_out):
        return {"pass": False, "error": f"uninterrupted child rc={rc}"}

    ck = os.path.join(tmp, "ck-kill")
    got_out = os.path.join(tmp, "got.npz")
    rc = _run_child(ck, got_out, kill_after_commits=2)
    if rc == -999:
        return {"pass": False, "error": "no 2 commits within 120s"}
    killed = rc == -signal.SIGKILL
    rc2 = _run_child(ck, got_out)  # resume in a fresh process
    if rc2 != 0 or not os.path.exists(got_out):
        return {"pass": False, "error": f"resumed child rc={rc2}"}

    ref = dict(np.load(ref_out))
    got = dict(np.load(got_out))
    identical = (set(ref) == set(got)
                 and all(np.array_equal(ref[k], got[k]) for k in ref))
    return {"pass": bool(killed and identical), "killed_mid_run": killed,
            "final_params_bit_identical": bool(identical)}


def gate_nan_rollback(tmp, monitor):
    """One injected NaN → exactly one rollback, finite completion, and no
    F802 on the watching monitor (clean path)."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.incubate.checkpoint import AutoCheckpoint
    from paddle_tpu.resilience import TrainingSupervisor
    from paddle_tpu.resilience import supervisor as sup_mod

    pt.seed(44)
    loader = _loader()
    model = _model(seed=1)
    acp = AutoCheckpoint(model, os.path.join(tmp, "ck-nan"), save_steps=3,
                         async_save=False, data_loader=loader)
    sup = TrainingSupervisor(acp, warmup_steps=2)
    base = sup_mod.stats()
    step, injected, losses = 0, False, []
    for epoch in range(2):
        for x, y in sup.steps(loader, epoch):
            loss, _ = model.train_batch([x], [y])
            step += 1
            lv = float(np.asarray(loss))
            if step == 5 and not injected:
                injected, lv = True, float("nan")
            if sup.guard(lv):
                losses.append(lv)
                acp.step(epoch)
        acp.epoch_end(epoch)
    acp.close()
    d = {k: sup_mod.stats()[k] - base[k] for k in base}
    f802_silent = not [x for x in monitor.diagnostics() if x.rule == "F802"]
    ok = (sup.rollbacks == 1 and d["rollbacks"] == 1
          and d["skipped_batches"] >= 1 and d["exact_resumes"] == 1
          and d["fatal_divergences"] == 0
          and bool(losses) and all(np.isfinite(losses)) and f802_silent)
    return {"pass": bool(ok), "rollbacks": sup.rollbacks,
            "skipped_batches": d["skipped_batches"],
            "exact_resumes": d["exact_resumes"],
            "finite_completion": bool(losses) and bool(np.all(np.isfinite(losses))),
            "f802_silent_on_clean_path": f802_silent}


def gate_wedged_collective():
    import numpy as np

    import paddle_tpu.distributed as dist
    from paddle_tpu.framework.errors import TransientDeviceError
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.resilience import FaultPlan
    from paddle_tpu.resilience import supervisor as sup_mod

    base = sup_mod.stats()["watchdog_trips"]
    plan = FaultPlan.parse("site=collective.call,every=1,latency_ms=10000")
    set_flags({"collective_timeout_s": 0.5})
    raised = elapsed = None
    try:
        with plan:
            t0 = time.monotonic()
            try:
                dist.all_reduce(np.ones((dist.get_world_size() or 1, 2),
                                        np.float32))
                raised = False
            except TransientDeviceError:
                raised = True
            elapsed = time.monotonic() - t0
    finally:
        set_flags({"collective_timeout_s": 0.0})
    trips = sup_mod.stats()["watchdog_trips"] - base
    ok = raised and elapsed < 5.0 and trips == 1
    return {"pass": bool(ok), "raised_within_deadline": bool(raised),
            "seconds": round(elapsed, 2), "watchdog_trips": trips}


def gate_rollback_loop_f802(tmp, monitor):
    import paddle_tpu as pt
    from paddle_tpu.framework.errors import DivergenceError
    from paddle_tpu.incubate.checkpoint import AutoCheckpoint
    from paddle_tpu.resilience import TrainingSupervisor

    pt.seed(44)
    loader = _loader()
    model = _model(seed=1)
    acp = AutoCheckpoint(model, os.path.join(tmp, "ck-loop"),
                         save_steps=100, async_save=False,
                         data_loader=loader)
    sup = TrainingSupervisor(acp, skip_batches=0)
    fatal = False
    try:
        for x, y in sup.steps(loader, 0):
            model.train_batch([x], [y])
            if sup.guard(float("nan")):
                acp.step(0)
    except DivergenceError:
        fatal = True
    finally:
        acp.close()
    fired = bool([x for x in monitor.diagnostics() if x.rule == "F802"])
    return {"pass": bool(fatal and fired), "fatal_divergence": fatal,
            "f802_fired": fired}


def gate_disabled_hooks(tmp):
    """Disabled supervisor + watchdog off: the wrapped loop is a plain
    loop — identical losses to the bare one, no baseline committed."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.framework.flags import get_flags
    from paddle_tpu.incubate.checkpoint import AutoCheckpoint
    from paddle_tpu.resilience import TrainingSupervisor

    def run(wrapped):
        pt.seed(31)
        loader = _loader()
        model = _model(seed=1)
        losses = []
        if wrapped:
            acp = AutoCheckpoint(model, os.path.join(tmp, "ck-off"),
                                 async_save=False, data_loader=loader)
            sup = TrainingSupervisor(acp, enable=False)
            for x, y in sup.steps(loader, 0):
                loss, _ = model.train_batch([x], [y])
                assert sup.guard(float(np.asarray(loss)))
                losses.append(float(np.asarray(loss)))
            acp.close()
            return losses, acp.latest_dir()
        for x, y in loader:
            loss, _ = model.train_batch([x], [y])
            losses.append(float(np.asarray(loss)))
        return losses, None

    bare, _ = run(wrapped=False)
    guarded, latest = run(wrapped=True)
    identical = bare == guarded  # exact float equality: falsy hooks only
    no_baseline = latest is None
    watchdog_off = get_flags("collective_timeout_s")["collective_timeout_s"] == 0.0
    ok = identical and no_baseline and watchdog_off
    return {"pass": bool(ok), "losses_bit_identical": bool(identical),
            "no_baseline_checkpoint": bool(no_baseline),
            "watchdog_flag_off": bool(watchdog_off)}


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--elastic-child":
        return elastic_child(sys.argv[2], sys.argv[3])
    from paddle_tpu.analysis import RetraceMonitor

    t0 = time.time()
    with tempfile.TemporaryDirectory() as tmp:
        with RetraceMonitor() as monitor:
            nan = gate_nan_rollback(tmp, monitor)
            loop = gate_rollback_loop_f802(tmp, monitor)
        wedge = gate_wedged_collective()
        disabled = gate_disabled_hooks(tmp)
        resume = gate_sigkill_exact_resume(tmp)
    gates = {"nan_rollback": nan, "rollback_loop_f802": loop,
             "wedged_collective": wedge, "disabled_hooks": disabled,
             "sigkill_exact_resume": resume}
    passed = all(g["pass"] for g in gates.values())
    print(json.dumps({"pass": bool(passed), **gates,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

"""Serving gate: closed compile set + exactness under live traffic (CPU).

One-command proof of the serving subsystem's two contracts, cheap enough
for every gate run:

1. **InferenceEngine** — export a small model, warm two buckets, fire
   mixed-shape traffic; the executable count must stay at exactly
   ``len(buckets)`` and every padded answer must match the direct
   predictor bit-for-bit (after unpadding).
2. **GenerationEngine** — batched ragged KV-cache greedy decode must be
   token-identical to the uncached full-recompute forward, with exactly
   ``len(prompt_buckets) + 1`` compiles.

Prints one JSON line; exit 0 iff both gates hold.
"""
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import nn  # noqa: E402
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM  # noqa: E402
from paddle_tpu.serving import (  # noqa: E402
    Bucket,
    GenerationEngine,
    InferenceEngine,
)


class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 4)

    def forward(self, x):
        return self.fc(x)


def gate_inference(tmp):
    pt.seed(7)
    net = _Net()
    prefix = os.path.join(tmp, "m")
    pt.inference.save_inference_model(
        prefix, net, [pt.static.InputSpec([None, None, 8], "float32")])
    with InferenceEngine(prefix, [Bucket(((4, 8),)), Bucket(((16, 8),))],
                         max_batch_size=4, max_queue_delay_ms=2.0) as eng:
        eng.warmup()
        rng = np.random.RandomState(0)
        xs = [rng.randn(n, 8).astype("float32")
              for n in (1, 3, 4, 2, 9, 16, 3, 11, 4, 7)]
        futs = [eng.submit([x]) for x in xs]
        ok = True
        for x, f in zip(xs, futs):
            got = f.result(120)[0]
            want = np.asarray(net(x[None]))[0]
            ok &= got.shape == want.shape and np.allclose(got, want,
                                                          atol=1e-5)
        st = eng.stats()
        closed = st["compile_count"] == 2 and st["bucket_misses"] == 0
        return {"exact": bool(ok), "closed_compile_set": bool(closed),
                "compile_count": st["compile_count"],
                "batches": st["batches"], "completed": st["completed"],
                "p99_ms": round(st["p99_ms"], 2)}


def gate_generation():
    import jax.numpy as jnp

    pt.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
                    max_position=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()

    def ref(prompt, n):
        ids, outs = list(map(int, prompt)), []
        for _ in range(n):
            logits = np.asarray(model(jnp.asarray([ids], jnp.int32)))[0]
            outs.append(int(np.argmax(logits[-1])))
            ids.append(outs[-1])
        return outs

    with GenerationEngine(model, prompt_buckets=[8, 16], batch_size=2,
                          max_queue_delay_ms=2.0) as eng:
        eng.warmup()
        prompts = [np.arange(5) % 97, (np.arange(7) * 3) % 97,
                   (np.arange(11) * 5 + 2) % 97]
        futs = [eng.submit(p, max_new_tokens=5) for p in prompts]
        gens = [f.result(300) for f in futs]
        identical = all(g.tolist() == ref(p, 5)
                        for p, g in zip(prompts, gens))
        st = eng.stats()
        # continuous (default): per-bucket slot-admission prefill + decode
        # + evict; legacy: per-bucket prefill + decode
        expected = (len([8, 16]) + 2 if st["continuous"]
                    else len([8, 16]) + 1)
        return {"token_identical": bool(identical),
                "continuous": bool(st["continuous"]),
                "closed_compile_set": st["compile_count"] == expected,
                "compile_count": st["compile_count"],
                "tokens": st["tokens"],
                "tokens_per_s": round(st["tokens_per_s"], 1)}


def main():
    t0 = time.time()
    with tempfile.TemporaryDirectory() as tmp:
        inf = gate_inference(tmp)
        gen = gate_generation()
    passed = (inf["exact"] and inf["closed_compile_set"]
              and gen["token_identical"] and gen["closed_compile_set"])
    print(json.dumps({"pass": bool(passed), "inference": inf,
                      "generation": gen,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

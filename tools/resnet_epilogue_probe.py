"""Does fusing BN statistics into the 1x1-conv GEMM pay on the chip?

tools/resnet_mfu_analysis.md (round 4) named Pallas BN/ReLU-epilogue
fusion as the bandwidth-side attack on ResNet-50's 1x1 layers.  This
probe measures it directly at the bottleneck shapes, train-mode BN:

  xla    conv1x1 -> batch mean/var -> normalize+relu   (XLA, 3 passes)
  fused  conv1x1_bn_stats kernel   -> normalize+relu   (2 passes)
  conv   bare conv1x1                                  (lower bound)

Run:  python tools/resnet_epilogue_probe.py        (ambient TPU)
One JSON line per (shape, variant); a closing line with the verdict.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

B = 128
# ResNet-50 bottleneck 1x1s (NHWC): (H=W, Cin, Cout)
SHAPES = [
    (56, 256, 64),
    (56, 64, 256),
    (28, 512, 128),
    (28, 128, 512),
    (14, 1024, 256),
    (14, 256, 1024),
    (7, 2048, 512),
    (7, 512, 2048),
]


def timed_chain(fn, x0, iters, *consts):
    import jax
    from jax import lax

    @jax.jit
    def chain(x, *consts):
        def body(x, _):
            return fn(x, *consts), None

        out, _ = lax.scan(body, x, None, length=iters)
        return out

    out = chain(x0, *consts)
    float(np.asarray(jax.tree_util.tree_leaves(out)[0].reshape(-1)[0]))
    t0 = time.perf_counter()
    out = chain(x0, *consts)
    float(np.asarray(jax.tree_util.tree_leaves(out)[0].reshape(-1)[0]))
    return time.perf_counter() - t0


def main():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.fused_conv1x1_bn import conv1x1_bn_relu

    print(json.dumps({"devices": [str(d) for d in jax.devices()],
                      "batch": B}), flush=True)
    key = jax.random.PRNGKey(0)
    iters = 100
    totals = {"xla": 0.0, "fused": 0.0, "conv": 0.0}
    for hw, cin, cout in SHAPES:
        M = B * hw * hw
        x = jax.random.normal(key, (M, cin), jnp.bfloat16)
        w = jax.random.normal(key, (cin, cout), jnp.bfloat16) * 0.05
        g = jnp.ones((cout,), jnp.float32)
        bt = jnp.zeros((cout,), jnp.float32)
        # carry-shape projector back to [M, cin]
        p = jax.random.normal(key, (cout, cin), jnp.bfloat16) * 0.05

        def xla_path(xx, w, g, bt, p):
            y = (xx @ w).astype(jnp.float32)
            mean = y.mean(0)
            var = y.var(0)
            out = jax.nn.relu((y - mean) * jax.lax.rsqrt(var + 1e-5)
                              * g + bt).astype(jnp.bfloat16)
            return out @ p

        def fused_path(xx, w, g, bt, p):
            out, _, _ = conv1x1_bn_relu(xx, w, g, bt)
            return out @ p

        def conv_path(xx, w, p):
            return ((xx @ w) @ p).astype(jnp.bfloat16)

        gflop = 2 * M * cin * cout * 2 * iters / 1e9  # incl. projector
        for name, fn, consts in (
                ("xla", xla_path, (w, g, bt, p)),
                ("fused", fused_path, (w, g, bt, p)),
                ("conv", conv_path, (w, p))):
            sec = timed_chain(fn, x, iters, *consts)
            ms = sec * 1e3 / iters
            totals[name] += ms
            print(json.dumps({
                "shape": f"{hw}x{hw}x{cin}->{cout}", "variant": name,
                "ms": round(ms, 4),
                "tflops": round(gflop / iters / ms, 1)}), flush=True)

    speedup = totals["xla"] / totals["fused"] if totals["fused"] else 0
    print(json.dumps({
        "metric": "conv1x1_bn_epilogue_fusion_speedup",
        "xla_ms_total": round(totals["xla"], 3),
        "fused_ms_total": round(totals["fused"], 3),
        "bare_conv_ms_total": round(totals["conv"], 3),
        "value": round(speedup, 3),
        "pays": speedup > 1.05,
    }), flush=True)


if __name__ == "__main__":
    main()

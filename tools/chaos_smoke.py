"""Chaos gate: crash-safe training resume + serving degradation (CPU).

One-command proof of the resilience subsystem's two core contracts, run
on every gate pass:

1. **Training chaos** — a child trainer runs with an injected transient
   checkpoint-write fault (``FLAGS_fault_plan`` via env, proving the
   retry path), gets SIGKILLed mid-epoch once enough checkpoints have
   committed, and the parent then resumes: restored params must be
   BIT-IDENTICAL to the last committed checkpoint file and the committed
   counter sequence must be gapless (the faulted write retried, not
   skipped).  A byte flip in the newest checkpoint must make a second
   resume quarantine it and land on the previous one.
2. **Serving chaos** — an in-process InferenceEngine with an injected
   non-transient runner fault: the per-bucket circuit must open, shed
   with ``UnavailableError`` while open, recover through a half-open
   probe once the fault plan is exhausted, and the batcher worker thread
   must survive the whole episode.

Also asserts the no-plan contract: with ``FLAGS_fault_plan`` unset,
``fault_point`` is inert and two identical CPU runs are bit-identical.

Both chaos paths run under the runtime lock-order sanitizer
(``FLAGS_lock_sanitizer=1``, inherited by the child trainer): a final
gate asserts zero C1004 cycles and zero C1005 long holds even while
faults fire, the circuit flaps, and the trainer is SIGKILLed.

Prints one JSON line; exit 0 iff every gate holds.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("FLAGS_lock_sanitizer", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRAIN_FAULT_PLAN = "site=checkpoint.write,nth=2,error=TransientDeviceError"


def _model(seed=0):
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as popt

    pt.seed(seed)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    model = pt.Model(net, inputs=["x"], labels=["y"])
    model.prepare(optimizer=popt.Adam(learning_rate=1e-2),
                  loss=nn.CrossEntropyLoss())
    return model


def train_child(ckpt_dir):
    """Subprocess body: train forever (the parent SIGKILLs us)."""
    import numpy as np

    from paddle_tpu.incubate.checkpoint import AutoCheckpoint

    model = _model(seed=1)
    acp = AutoCheckpoint(model, ckpt_dir, save_steps=3, keep_max=100)
    rng = np.random.RandomState(0)
    epoch = 0
    while True:
        x = rng.randn(16, 4).astype(np.float32)
        y = rng.randint(0, 2, size=(16,)).astype(np.int32)
        model.train_batch([x], [y])
        acp.step(epoch)
        time.sleep(0.01)  # give the parent a window to SIGKILL mid-epoch


def _committed(ckpt_dir):
    from paddle_tpu.incubate.checkpoint import _META, _PREFIX

    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(n for n in os.listdir(ckpt_dir)
                  if n.startswith(_PREFIX)
                  and os.path.exists(os.path.join(ckpt_dir, n, _META)))


def gate_training_chaos(tmp):
    import numpy as np

    from paddle_tpu.framework import serialization
    from paddle_tpu.incubate.checkpoint import AutoCheckpoint

    ckpt_dir = os.path.join(tmp, "ck")
    env = dict(os.environ, FLAGS_fault_plan=TRAIN_FAULT_PLAN)
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--train-child",
         ckpt_dir],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    try:
        deadline = time.time() + 120
        while len(_committed(ckpt_dir)) < 3 and time.time() < deadline:
            if child.poll() is not None:
                return {"pass": False,
                        "error": f"trainer died rc={child.returncode} "
                                 f"before 3 checkpoints committed"}
            time.sleep(0.05)
        committed = _committed(ckpt_dir)
        if len(committed) < 3:
            return {"pass": False, "error": "no 3 checkpoints within 120s"}
    finally:
        if child.poll() is None:
            child.send_signal(signal.SIGKILL)  # crash, not clean shutdown
        child.wait()

    # the nth=2 write fault was TRANSIENT: the retry must have landed it,
    # so committed counters are gapless from 1
    counters = [serialization.load(os.path.join(ckpt_dir, n,
                                                "meta.pdmeta"))["counter"]
                for n in _committed(ckpt_dir)]
    gapless = counters == list(range(1, len(counters) + 1))

    # resume (fresh process state: different-seed model) and compare the
    # restored params bit-for-bit against the last committed file
    newest = _committed(ckpt_dir)[-1]
    want = serialization.load(os.path.join(ckpt_dir, newest, "m.pdparams"))
    m2 = _model(seed=9)
    acp2 = AutoCheckpoint(m2, ckpt_dir)
    meta = acp2.resume()
    restored = {k: np.asarray(v) for k, v in m2.network.state_dict().items()}
    identical = (meta is not None
                 and set(want) == set(restored)
                 and all(np.array_equal(want[k], restored[k])
                         for k in want))

    # corruption fallback: flip one byte in the newest payload; the next
    # resume must quarantine it and land on the PREVIOUS checkpoint
    p = os.path.join(ckpt_dir, newest, "m.pdparams")
    blob = bytearray(open(p, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(p, "wb").write(bytes(blob))
    prev = _committed(ckpt_dir)[-2]
    prev_meta = serialization.load(os.path.join(ckpt_dir, prev,
                                                "meta.pdmeta"))
    m3 = _model(seed=13)
    acp3 = AutoCheckpoint(m3, ckpt_dir)
    meta3 = acp3.resume()
    quarantined = any(n.startswith("corrupt-") for n in os.listdir(ckpt_dir))
    fell_back = (meta3 is not None
                 and meta3["counter"] == prev_meta["counter"])

    ok = gapless and identical and quarantined and fell_back
    return {"pass": bool(ok), "committed": len(counters),
            "counters_gapless": bool(gapless),
            "resume_bit_identical": bool(identical),
            "corrupt_quarantined": bool(quarantined),
            "fell_back_to_previous": bool(fell_back)}


def gate_serving_chaos(tmp):
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.framework.errors import UnavailableError
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.resilience import FaultPlan
    from paddle_tpu.serving import Bucket, InferenceEngine

    pt.seed(7)
    net = nn.Linear(8, 4)
    prefix = os.path.join(tmp, "m")
    pt.inference.save_inference_model(
        prefix, net, [pt.static.InputSpec([None, 8], "float32")])

    # a tight breaker so the episode fits in a smoke run
    set_flags({"circuit_window": 2, "circuit_cooldown_ms": 200.0,
               "circuit_half_open_probes": 1})
    # non-transient fault (RuntimeError without a transient status string)
    # so the retry path stays out of the way and failures hit the breaker;
    # times matches the window: once the circuit opens the plan is spent,
    # so the post-cooldown half-open probe succeeds
    plan = FaultPlan.parse("site=serving.runner,every=1,times=2,"
                           "error=RuntimeError")
    x = np.ones((8,), np.float32)
    outcomes = []
    with InferenceEngine(prefix, [Bucket(((8,),))], max_batch_size=1,
                         max_queue_delay_ms=1.0) as eng:
        eng.warmup()
        with plan:
            for i in range(8):
                try:
                    eng.infer([x], timeout=10)
                    outcomes.append("ok")
                except UnavailableError:
                    outcomes.append("shed")
                except RuntimeError:
                    outcomes.append("err")
            # circuit open: wait out the cooldown; the fault plan's
            # times=4 cap is exhausted, so the half-open probe succeeds
            time.sleep(0.3)
            recovered = np.allclose(eng.infer([x], timeout=10),
                                    [np.asarray(net(x[None]))[0]],
                                    atol=1e-5)
        worker_alive = eng._batcher._worker.is_alive()
        st = eng.stats()

    opened = "shed" in outcomes
    only_errs_then_sheds = ("err" in outcomes and outcomes.index("shed")
                            > outcomes.index("err")) if opened else False
    ok = opened and only_errs_then_sheds and recovered and worker_alive
    return {"pass": bool(ok), "outcomes": outcomes,
            "circuit_opened": bool(opened), "recovered": bool(recovered),
            "worker_alive": bool(worker_alive),
            "circuit_shed": st["circuit_shed"], "errors": st["errors"]}


def gate_noop_determinism():
    """With no fault plan, fault_point is inert and runs are bit-identical."""
    import numpy as np

    from paddle_tpu.resilience import faults

    if faults.active():
        return {"pass": False, "error": "a fault plan leaked into the gate"}

    def run():
        import jax.numpy as jnp

        m = _model(seed=5)
        rng = np.random.RandomState(3)
        x = rng.randn(16, 4).astype(np.float32)
        y = rng.randint(0, 2, size=(16,)).astype(np.int32)
        losses = [float(np.asarray(m.train_batch([x], [y])[0]).reshape(-1)[0])
                  for _ in range(3)]
        del jnp
        return losses

    a, b = run(), run()
    identical = a == b  # exact float equality: bit-identical CPU math
    return {"pass": bool(identical), "losses": a}


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--train-child":
        train_child(sys.argv[2])
        return 0
    t0 = time.time()
    with tempfile.TemporaryDirectory() as tmp:
        train = gate_training_chaos(tmp)
        serving = gate_serving_chaos(tmp)
        noop = gate_noop_determinism()

    from paddle_tpu.framework import locking
    lk = locking.stats()
    sanitizer = {"pass": bool(lk["enabled"] and lk["cycles"] == 0
                              and lk["long_holds"] == 0),
                 "enabled": lk["enabled"], "acquires": lk["acquires"],
                 "edges": lk["edges"], "cycles": lk["cycles"],
                 "long_holds": lk["long_holds"],
                 "violations": locking.violations()[:4]}

    passed = (train["pass"] and serving["pass"] and noop["pass"]
              and sanitizer["pass"])
    print(json.dumps({"pass": bool(passed), "training_chaos": train,
                      "serving_chaos": serving, "noop_determinism": noop,
                      "lock_sanitizer": sanitizer,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

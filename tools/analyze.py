#!/usr/bin/env python
"""Run the paddle_tpu static-analysis passes over modules or scripts.

Thin wrapper over ``python -m paddle_tpu.analysis`` so the tool is
discoverable next to the other repo tooling; see that module (or README
"Static analysis") for flags and the rule catalog.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from paddle_tpu.analysis import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())

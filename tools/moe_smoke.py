"""Expert-sharded decode gate: closed compile set + balanced routing (CPU).

One-command proof of the MoE serving contracts (paddle_tpu/moe):

1. **Closed compile set, tokens exact** — a 4-expert top-2 GPT behind the
   continuous-batching engine decodes with the per-step router INSIDE the
   jitted step: ``compile_count`` stays at ``len(prompt_buckets) + 2`` and
   zero post-warmup XLA compile requests fire.  With ample expert capacity
   (``moe_capacity_factor >= num_experts`` ⇒ no token ever dropped) the
   generated tokens are bit-identical to the eager greedy reference —
   routing inside the engine's padded batch changes nothing.
2. **Occupancy counters on the bus** — the ``("serving", <name>)``
   snapshot carries the ``moe_routed_tokens`` / ``moe_dropped_tokens`` /
   post-warmup step counters plus the ``moe_overflow_frac`` and
   ``moe_dead_experts`` gauges; the healthy run must show every expert
   receiving traffic (no dead experts), zero overflow, and rule S606
   silent on a live RetraceMonitor.
3. **Zero-expert config untouched** — the same engine build with
   ``moe_experts=0`` produces identical tokens to an unwrapped dense run
   and publishes NO moe keys (the tap is never installed).

Prints one JSON line; exit 0 iff all three gates hold.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.monitoring  # noqa: E402
import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.analysis import RetraceMonitor  # noqa: E402
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM  # noqa: E402
from paddle_tpu.serving import GenerationEngine  # noqa: E402

BUCKETS = [16]
EXPERTS = 4
REQS = 6
NEW_TOKENS = 12

# ground truth for "zero post-warmup recompiles": actual XLA backend
# compile requests, which fire even when the jaxpr cache hits
_XLA_COMPILES = [0]
jax.monitoring.register_event_listener(
    lambda name, **kw: _XLA_COMPILES.__setitem__(0, _XLA_COMPILES[0] + 1)
    if name == "/jax/compilation_cache/compile_requests_use_cache" else None)


def _model(experts: int):
    pt.seed(21)
    # capacity_factor = num_experts makes C = top_k * tokens: no token can
    # overflow, so engine-batched routing is per-token independent and the
    # tokens must match the eager reference bit-for-bit
    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=4, max_position=128, dropout=0.0,
                    moe_experts=experts, moe_top_k=2,
                    moe_capacity_factor=float(max(experts, 1)),
                    moe_jitter=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _ref(model, prompt, n):
    import jax.numpy as jnp
    ids, outs = list(map(int, prompt)), []
    for _ in range(n):
        logits = np.asarray(model(jnp.asarray([ids], jnp.int32)))[0]
        outs.append(int(np.argmax(logits[-1])))
        ids.append(outs[-1])
    return outs


def _drive(model, name):
    """Run the mixed workload; returns (outs, refs, engine stats, compile
    accounting)."""
    rng = np.random.RandomState(4)
    prompts = [rng.randint(1, 97, size=4 + (k % 9)).astype(np.int32)
               for k in range(REQS)]
    refs = [_ref(model, p, NEW_TOKENS) for p in prompts]
    with GenerationEngine(model, prompt_buckets=BUCKETS, batch_size=2,
                          continuous=True, name=name) as eng:
        warm = eng.warmup()
        xla0 = _XLA_COMPILES[0]
        futs = [eng.submit(p, NEW_TOKENS) for p in prompts]
        outs = [f.result(600).tolist() for f in futs]
        # the harvest is one step deferred; one more publish closes it out
        time.sleep(0.05)
        st = eng.stats()
        compiles = eng.compile_count
    return {"outs": outs, "refs": refs, "stats": st, "warm": warm,
            "compiles": compiles, "xla": _XLA_COMPILES[0] - xla0}


def gate_moe():
    with RetraceMonitor() as mon:
        r = _drive(_model(EXPERTS), "moe-smoke")
        s606 = [d for d in mon.diagnostics() if d.rule == "S606"]
    st = r["stats"]
    routed = int(st.get("moe_routed_tokens", 0))
    dropped = int(st.get("moe_dropped_tokens", 0))
    sampled = int(st.get("moe_sampled_steps_after_warm", 0))
    return {
        "token_identical": bool(r["outs"] == r["refs"]),
        "warmup_compiles": r["warm"],
        "closed_compile_set": (r["compiles"] == len(BUCKETS) + 2
                               and r["xla"] == 0),
        "xla_recompiles_post_warmup": r["xla"],
        "moe_routed_tokens": routed,
        "moe_dropped_tokens": dropped,
        "moe_sampled_steps_after_warm": sampled,
        "moe_overflow_frac": float(st.get("moe_overflow_frac", -1.0)),
        "moe_dead_experts": float(st.get("moe_dead_experts", -1.0)),
        "counters_flow": bool(routed > 0 and sampled > 0),
        "balanced": bool(dropped == 0
                         and float(st.get("moe_overflow_frac", 1.0)) == 0.0
                         and float(st.get("moe_dead_experts", 1.0)) == 0.0),
        "s606_silent": not s606,
    }


def gate_dense():
    r = _drive(_model(0), "moe-smoke-dense")
    moe_keys = [k for k in r["stats"] if k.startswith("moe_")]
    return {
        "token_identical": bool(r["outs"] == r["refs"]),
        "closed_compile_set": (r["compiles"] == len(BUCKETS) + 2
                               and r["xla"] == 0),
        "no_moe_keys": not moe_keys,
        "moe_keys": moe_keys,
    }


def main():
    t0 = time.time()
    moe = gate_moe()
    dense = gate_dense()
    passed = (moe["token_identical"] and moe["closed_compile_set"]
              and moe["counters_flow"] and moe["balanced"]
              and moe["s606_silent"]
              and dense["token_identical"] and dense["closed_compile_set"]
              and dense["no_moe_keys"])
    print(json.dumps({"pass": bool(passed), "moe": moe, "dense": dense,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

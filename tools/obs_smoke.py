"""Observability gate: live scrape + JSONL + serving spans (CPU).

One-command proof of the observability subsystem's core contracts:

1. **Live scrape** — ``observability.enable(port=-1, jsonl=...)``, a
   short DataLoader-fed training loop through ``Executor.run``, then two
   HTTP scrapes of the Prometheus endpoint: the text must contain an
   advancing ``paddle_tpu_steps_total``, a ``paddle_tpu_data_wait_ms``
   histogram, and the HBM high-water gauge (0 on CPU — present, not
   populated).
2. **JSONL sink** — the per-process snapshot file gains >= 2 records at
   a fast interval and ``merge_jsonl`` returns a time-ordered stream.
3. **Serving spans** — a ``MicroBatcher`` request served while a
   profiler run is live lands ``<name>/queue`` + ``<name>/execute``
   events with ``cat == "serving"`` and a shared span id in the exported
   chrome trace.
4. **Off means off** — with observability disabled, the Executor's
   steptrace hook is a single falsy module-attribute check
   (``steptrace._active is None``) and no endpoint is listening.

Prints one JSON line; exit 0 iff every gate holds.
"""
import json
import os
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _scrape(url):
    return urllib.request.urlopen(url, timeout=10).read().decode()


def _metric_value(text, name):
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            return float(line.rsplit(" ", 1)[1])
    return None


def gate_training_scrape_and_jsonl(result):
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu import observability as obs
    from paddle_tpu.io import DataLoader, TensorDataset
    from paddle_tpu.static.graph import reset_default_programs

    tmp = tempfile.mkdtemp(prefix="obs_smoke_")
    base = os.path.join(tmp, "metrics.jsonl")
    paddle.seed(0)
    reset_default_programs()
    obs.enable(port=-1, jsonl=base, jsonl_interval_s=0.2)
    try:
        status = obs.status()
        assert status["enabled"] and status["port"] > 0, status

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 13])
            y = fluid.data("y", [-1, 1])
            h = fluid.layers.fc(x, 8, act="relu")
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)

        rng = np.random.RandomState(0)
        ds = TensorDataset([rng.rand(64, 13).astype(np.float32),
                            rng.rand(64, 1).astype(np.float32)])
        loader = DataLoader(ds, batch_size=8)

        def epoch():
            for xb, yb in loader:
                exe.run(main, feed={"x": np.asarray(xb),
                                    "y": np.asarray(yb)},
                        fetch_list=[loss])

        epoch()
        first = _scrape(status["url"])
        steps_a = _metric_value(first, "paddle_tpu_steps_total")
        assert steps_a == 8.0, f"steps after epoch 1: {steps_a}"
        epoch()
        second = _scrape(status["url"])
        steps_b = _metric_value(second, "paddle_tpu_steps_total")
        assert steps_b == 16.0, f"steps after epoch 2: {steps_b}"
        assert "paddle_tpu_data_wait_ms_bucket{" in second, \
            "data_wait_ms histogram missing from scrape"
        assert _metric_value(second, "paddle_tpu_hbm_high_water_bytes") \
            is not None, "HBM high-water gauge missing from scrape"
        assert "paddle_tpu_executor_cache_hits{" in second, \
            "trace_events bridge family missing from scrape"
        result["steps_scraped"] = steps_b
        result["scrape_bytes"] = len(second)

        # jsonl: give the 0.2s writer time for >= 2 records
        time.sleep(0.6)
        snap = obs.steptrace.active().snapshot()
        result["steptrace"] = {k: snap[k] for k in
                               ("steps", "examples", "data_wait_ms",
                                "dispatch_ms", "device_ms", "steps_per_s")}
    finally:
        obs.disable()
        reset_default_programs()
    from paddle_tpu.observability import exporters

    path = exporters.process_jsonl_path(base)
    lines = open(path).readlines()
    assert len(lines) >= 2, f"jsonl records: {len(lines)}"
    merged = exporters.merge_jsonl(base)
    ts = [r["ts"] for r in merged]
    assert ts == sorted(ts), "merge_jsonl not time-ordered"
    result["jsonl_records"] = len(lines)

    # off means off: hook is a falsy module attribute, endpoint gone
    from paddle_tpu.observability import steptrace

    assert steptrace._active is None, "steptrace still active after disable"
    try:
        _scrape(status["url"])
        raise AssertionError("endpoint still answering after disable")
    except (OSError, urllib.error.URLError):
        pass


def gate_serving_spans(result):
    from paddle_tpu import profiler as prof
    from paddle_tpu.serving.batcher import MicroBatcher

    prof.reset_profiler()
    prof.start_profiler()
    try:
        with MicroBatcher(lambda ins: 0,
                          lambda bucket, reqs: [0] * len(reqs),
                          max_batch_size=4, max_queue_delay_ms=1.0,
                          name="obs_smoke") as mb:
            futs = [mb.submit(([i],)) for i in range(3)]
            for f in futs:
                f.result(10)
    finally:
        prof.stop_profiler(profile_path=None)
    tmp = tempfile.mkdtemp(prefix="obs_smoke_trace_")
    path = os.path.join(tmp, "trace.json")
    prof.export_chrome_tracing(path)
    evs = json.load(open(path))["traceEvents"]
    serving = [e for e in evs if e.get("cat") == "serving"]
    names = {e["name"] for e in serving}
    assert "obs_smoke/queue" in names and "obs_smoke/execute" in names, \
        f"serving span names: {sorted(names)}"
    spans = {e["args"]["span"] for e in serving}
    assert len(spans) == 3, f"expected 3 request span ids, got {spans}"
    prof.reset_profiler()
    result["serving_span_events"] = len(serving)
    result["serving_span_ids"] = len(spans)


def main():
    result = {"gate": "obs_smoke", "ok": False}
    gate_training_scrape_and_jsonl(result)
    gate_serving_spans(result)
    result["ok"] = True
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Continuous-batching gate: HOL blocking killed, zero loss, closed set (CPU).

One-command proof of the decode data plane's contracts, cheap enough for
every gate run:

1. **Token identity + closed compile set** — mixed-length prompts with
   staggered admission mid-decode must decode token-identical to uncached
   greedy, with zero post-warmup recompiles (``compile_count`` stays at
   ``len(prompt_buckets) + 2``).
2. **Head-of-line blocking** — 1 long request + many short ones under
   live traffic: the continuous engine's short-request p99 must be at
   least 2x better than the legacy run-batch-to-completion path's under
   the long-request stall, with zero lost requests on both.
3. **Router probe compat** — a health-probed :class:`Router` over two
   continuous engines stays green (``synthetic_inputs`` probes succeed,
   routed generations are token-identical).

Prints one JSON line; exit 0 iff all three gates hold.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.monitoring  # noqa: E402
import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM  # noqa: E402
from paddle_tpu.serving import GenerationEngine, Router  # noqa: E402

BUCKETS = [8, 16]
LONG_TOKENS = 240  # prompt 12 + 240 stays inside the 256-slot ring (exact)
SHORTS = 6
SHORT_TOKENS = 3

# ground truth for "zero post-warmup recompiles": count actual XLA backend
# compile requests, which fire even when the jaxpr cache hits (e.g. the
# silent placement-specialised recompiles the trace counter cannot see)
_XLA_COMPILES = [0]
jax.monitoring.register_event_listener(
    lambda name, **kw: _XLA_COMPILES.__setitem__(0, _XLA_COMPILES[0] + 1)
    if name == "/jax/compilation_cache/compile_requests_use_cache" else None)


def _model():
    pt.seed(11)
    # hidden 128 puts the decode step around a millisecond on CPU, so the
    # legacy path's head-of-line stall is long enough to measure cleanly
    cfg = GPTConfig(vocab_size=97, hidden_size=128, num_layers=2,
                    num_heads=4, max_position=256, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _ref(model, prompt, n):
    import jax.numpy as jnp
    ids, outs = list(map(int, prompt)), []
    for _ in range(n):
        logits = np.asarray(model(jnp.asarray([ids], jnp.int32)))[0]
        outs.append(int(np.argmax(logits[-1])))
        ids.append(outs[-1])
    return outs


def _mixed_traffic(eng):
    """1 long + SHORTS shorts submitted while the long one decodes.
    Returns (long_latency_s, [short_latency_s], results, lost)."""
    rng = np.random.RandomState(3)
    long_p = rng.randint(1, 97, size=12).astype(np.int32)
    shorts = [rng.randint(1, 97, size=3 + (k % 5)).astype(np.int32)
              for k in range(SHORTS)]
    done = {}

    def track(key, fut, t0):
        fut.add_done_callback(
            lambda f: done.setdefault(key, time.monotonic() - t0))
        return fut

    t0 = time.monotonic()
    fl = track("long", eng.submit(long_p, LONG_TOKENS), t0)
    time.sleep(0.01)  # the long request is decoding by now
    fs = []
    for k, p in enumerate(shorts):
        fs.append(track(k, eng.submit(p, SHORT_TOKENS), time.monotonic()))
    lost = 0
    results = {}
    try:
        results["long"] = fl.result(600).tolist()
    except Exception:
        lost += 1
    for k, f in enumerate(fs):
        try:
            results[k] = f.result(600).tolist()
        except Exception:
            lost += 1
    lat = sorted(done[k] for k in range(SHORTS) if k in done)
    p99 = lat[min(int(round(0.99 * len(lat))), len(lat) - 1)] if lat else -1.0
    return done.get("long", -1.0), p99, (long_p, shorts, results), lost


def gate_hol(model):
    with GenerationEngine(model, prompt_buckets=BUCKETS, batch_size=2,
                          continuous=True, name="gen-smoke-cont") as cont:
        warm = cont.warmup()
        xla0 = _XLA_COMPILES[0]
        _, cont_p99, (long_p, shorts, results), cont_lost = \
            _mixed_traffic(cont)
        xla_recompiles = _XLA_COMPILES[0] - xla0
        compiles = cont.compile_count
    with GenerationEngine(model, prompt_buckets=BUCKETS, batch_size=2,
                          max_queue_delay_ms=1.0, continuous=False,
                          name="gen-smoke-leg") as leg:
        leg.warmup()
        _, leg_p99, (_, _, leg_results), leg_lost = _mixed_traffic(leg)

    identical = (results.get("long") == _ref(model, long_p, LONG_TOKENS)
                 and all(results.get(k) == _ref(model, p, SHORT_TOKENS)
                         for k, p in enumerate(shorts)))
    legacy_identical = all(results.get(k) == leg_results.get(k)
                           for k in list(range(SHORTS)) + ["long"])
    return {
        "token_identical": bool(identical),
        "matches_legacy": bool(legacy_identical),
        "warmup_compiles": warm,
        "closed_compile_set": (compiles == len(BUCKETS) + 2
                               and xla_recompiles == 0),
        "xla_recompiles_post_warmup": xla_recompiles,
        "lost": cont_lost + leg_lost,
        "short_p99_ms": round(cont_p99 * 1e3, 1),
        "legacy_short_p99_ms": round(leg_p99 * 1e3, 1),
        "hol_speedup": round(leg_p99 / cont_p99, 1) if cont_p99 > 0 else 0.0,
        "hol_2x": bool(cont_p99 > 0 and leg_p99 >= 2.0 * cont_p99),
    }


def gate_router_probe(model):
    engines = [GenerationEngine(model, prompt_buckets=BUCKETS, batch_size=2,
                                continuous=True, name=f"gen-smoke-r{i}")
               for i in range(2)]
    router = Router(engines, name="gen-smoke-router", probe_interval_s=0.2)
    try:
        router.warmup()
        rng = np.random.RandomState(5)
        prompts = [rng.randint(1, 97, size=4 + k).astype(np.int32)
                   for k in range(4)]
        outs = [router.submit(p, max_new_tokens=3).result(120).tolist()
                for p in prompts]
        identical = all(o == _ref(model, p, 3)
                        for p, o in zip(prompts, outs))
        time.sleep(0.6)  # a few background probe sweeps
        st = router.stats()
        return {"routed_identical": bool(identical),
                "healthy": router.healthy_count(),
                "replicas": len(engines),
                "probes": st.get("probes", 0),
                "probe_failures": st.get("probe_failures", 0)}
    finally:
        router.close(timeout=30)  # close_engines=True: replicas too


def main():
    t0 = time.time()
    model = _model()
    hol = gate_hol(model)
    probe = gate_router_probe(model)
    passed = (hol["token_identical"] and hol["matches_legacy"]
              and hol["closed_compile_set"] and hol["lost"] == 0
              and hol["hol_2x"]
              and probe["routed_identical"]
              and probe["healthy"] == probe["replicas"]
              and probe["probe_failures"] == 0)
    print(json.dumps({"pass": bool(passed), "hol": hol, "probe": probe,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

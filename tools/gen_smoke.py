"""Continuous-batching gate: HOL blocking killed, zero loss, closed set (CPU).

One-command proof of the decode data plane's contracts, cheap enough for
every gate run:

1. **Token identity + closed compile set** — mixed-length prompts with
   staggered admission mid-decode must decode token-identical to uncached
   greedy, with zero post-warmup recompiles (``compile_count`` stays at
   ``len(prompt_buckets) + 2``).
2. **Head-of-line blocking** — 1 long request + many short ones under
   live traffic: the continuous engine's short-request p99 must be at
   least 2x better than the legacy run-batch-to-completion path's under
   the long-request stall, with zero lost requests on both.
3. **Router probe compat** — a health-probed :class:`Router` over two
   continuous engines stays green (``synthetic_inputs`` probes succeed,
   routed generations are token-identical).
4. **Paged KV + speculative decoding** — the same mixed shared-prefix
   workload through a paged engine holding TWICE the resident slots of
   the dense baseline in the SAME HBM budget (dense ``2 slots x 256``
   ring = 32 pages of 16; paged pool = those same 32 pages backing 4
   slots): peak resident slots strictly higher, tokens/s no worse,
   tokens bit-identical to uncached greedy, zero post-warmup XLA
   compiles on the paged compile set (``len(prompt_buckets) + 3``).

Prints one JSON line; exit 0 iff all four gates hold.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.monitoring  # noqa: E402
import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM  # noqa: E402
from paddle_tpu.serving import GenerationEngine, Router  # noqa: E402

BUCKETS = [8, 16]
LONG_TOKENS = 240  # prompt 12 + 240 stays inside the 256-slot ring (exact)
SHORTS = 6
SHORT_TOKENS = 3

# paged gate geometry: the dense baseline's HBM budget (2 slots x 256
# ring slots) expressed in pages of 16 — the paged engine gets exactly
# that page pool and must hold strictly more resident slots in it
CACHE = 256
PAGE_SIZE = 16
DENSE_SLOTS = 2
POOL_PAGES = DENSE_SLOTS * CACHE // PAGE_SIZE  # 32 pages = same bytes
PAGED_SLOTS = 4
PAGED_REQS = 12
PAGED_TOKENS = 32
PREFIX_LEN = 20  # shared system prompt: 1 full page + a CoW'd boundary

# ground truth for "zero post-warmup recompiles": count actual XLA backend
# compile requests, which fire even when the jaxpr cache hits (e.g. the
# silent placement-specialised recompiles the trace counter cannot see)
_XLA_COMPILES = [0]
jax.monitoring.register_event_listener(
    lambda name, **kw: _XLA_COMPILES.__setitem__(0, _XLA_COMPILES[0] + 1)
    if name == "/jax/compilation_cache/compile_requests_use_cache" else None)


def _model():
    pt.seed(11)
    # hidden 128 puts the decode step around a millisecond on CPU, so the
    # legacy path's head-of-line stall is long enough to measure cleanly
    cfg = GPTConfig(vocab_size=97, hidden_size=128, num_layers=2,
                    num_heads=4, max_position=256, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _paged_model():
    pt.seed(13)
    # hidden 32 keeps the CPU decode step dispatch-dominated rather than
    # FLOP-dominated — the regime the paged gate is about (accelerator
    # decode is latency-bound, so batching 4 slots x 5 verify positions
    # into one step costs ~one step, not 20 token-forwards)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_position=CACHE, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _ref(model, prompt, n):
    import jax.numpy as jnp
    ids, outs = list(map(int, prompt)), []
    for _ in range(n):
        logits = np.asarray(model(jnp.asarray([ids], jnp.int32)))[0]
        outs.append(int(np.argmax(logits[-1])))
        ids.append(outs[-1])
    return outs


def _mixed_traffic(eng):
    """1 long + SHORTS shorts submitted while the long one decodes.
    Returns (long_latency_s, [short_latency_s], results, lost)."""
    rng = np.random.RandomState(3)
    long_p = rng.randint(1, 97, size=12).astype(np.int32)
    shorts = [rng.randint(1, 97, size=3 + (k % 5)).astype(np.int32)
              for k in range(SHORTS)]
    done = {}

    def track(key, fut, t0):
        fut.add_done_callback(
            lambda f: done.setdefault(key, time.monotonic() - t0))
        return fut

    t0 = time.monotonic()
    fl = track("long", eng.submit(long_p, LONG_TOKENS), t0)
    time.sleep(0.01)  # the long request is decoding by now
    fs = []
    for k, p in enumerate(shorts):
        fs.append(track(k, eng.submit(p, SHORT_TOKENS), time.monotonic()))
    lost = 0
    results = {}
    try:
        results["long"] = fl.result(600).tolist()
    except Exception:
        lost += 1
    for k, f in enumerate(fs):
        try:
            results[k] = f.result(600).tolist()
        except Exception:
            lost += 1
    lat = sorted(done[k] for k in range(SHORTS) if k in done)
    p99 = lat[min(int(round(0.99 * len(lat))), len(lat) - 1)] if lat else -1.0
    return done.get("long", -1.0), p99, (long_p, shorts, results), lost


def gate_hol(model):
    with GenerationEngine(model, prompt_buckets=BUCKETS, batch_size=2,
                          continuous=True, name="gen-smoke-cont") as cont:
        warm = cont.warmup()
        xla0 = _XLA_COMPILES[0]
        _, cont_p99, (long_p, shorts, results), cont_lost = \
            _mixed_traffic(cont)
        xla_recompiles = _XLA_COMPILES[0] - xla0
        compiles = cont.compile_count
    with GenerationEngine(model, prompt_buckets=BUCKETS, batch_size=2,
                          max_queue_delay_ms=1.0, continuous=False,
                          name="gen-smoke-leg") as leg:
        leg.warmup()
        _, leg_p99, (_, _, leg_results), leg_lost = _mixed_traffic(leg)

    identical = (results.get("long") == _ref(model, long_p, LONG_TOKENS)
                 and all(results.get(k) == _ref(model, p, SHORT_TOKENS)
                         for k, p in enumerate(shorts)))
    legacy_identical = all(results.get(k) == leg_results.get(k)
                           for k in list(range(SHORTS)) + ["long"])
    return {
        "token_identical": bool(identical),
        "matches_legacy": bool(legacy_identical),
        "warmup_compiles": warm,
        "closed_compile_set": (compiles == len(BUCKETS) + 2
                               and xla_recompiles == 0),
        "xla_recompiles_post_warmup": xla_recompiles,
        "lost": cont_lost + leg_lost,
        "short_p99_ms": round(cont_p99 * 1e3, 1),
        "legacy_short_p99_ms": round(leg_p99 * 1e3, 1),
        "hol_speedup": round(leg_p99 / cont_p99, 1) if cont_p99 > 0 else 0.0,
        "hol_2x": bool(cont_p99 > 0 and leg_p99 >= 2.0 * cont_p99),
    }


def gate_router_probe(model):
    engines = [GenerationEngine(model, prompt_buckets=BUCKETS, batch_size=2,
                                continuous=True, name=f"gen-smoke-r{i}")
               for i in range(2)]
    router = Router(engines, name="gen-smoke-router", probe_interval_s=0.2)
    try:
        router.warmup()
        rng = np.random.RandomState(5)
        prompts = [rng.randint(1, 97, size=4 + k).astype(np.int32)
                   for k in range(4)]
        outs = [router.submit(p, max_new_tokens=3).result(120).tolist()
                for p in prompts]
        identical = all(o == _ref(model, p, 3)
                        for p, o in zip(prompts, outs))
        time.sleep(0.6)  # a few background probe sweeps
        st = router.stats()
        return {"routed_identical": bool(identical),
                "healthy": router.healthy_count(),
                "replicas": len(engines),
                "probes": st.get("probes", 0),
                "probe_failures": st.get("probe_failures", 0)}
    finally:
        router.close(timeout=30)  # close_engines=True: replicas too


def gate_paged(model):
    """Dense 2-slot ring vs a paged 4-slot engine over the SAME 32-page
    HBM budget, on one shared-prefix workload: strictly more resident
    slots, tokens/s no worse, bit-identical, zero post-warmup compiles."""
    rng = np.random.RandomState(7)
    sysp = rng.randint(1, 97, size=PREFIX_LEN).astype(np.int32)
    prompts = [np.concatenate([sysp, rng.randint(1, 97, size=2 + (k % 7))])
               .astype(np.int32) for k in range(PAGED_REQS)]
    refs = [_ref(model, p, PAGED_TOKENS) for p in prompts]

    def run(paged):
        if paged:
            eng = GenerationEngine(
                model, prompt_buckets=[32], batch_size=PAGED_SLOTS,
                cache_len=CACHE, continuous=True, paged=True,
                kv_pages=POOL_PAGES, kv_page_size=PAGE_SIZE,
                speculative_k=4, name="gen-smoke-paged")
        else:
            eng = GenerationEngine(
                model, prompt_buckets=[32], batch_size=DENSE_SLOTS,
                cache_len=CACHE, continuous=True, name="gen-smoke-dense")
        nslots = PAGED_SLOTS if paged else DENSE_SLOTS
        with eng:
            warm = eng.warmup()
            xla0 = _XLA_COMPILES[0]
            t0 = time.monotonic()
            futs = [eng.submit(p, PAGED_TOKENS, prefix_key="sys",
                               prefix_len=PREFIX_LEN) for p in prompts]
            # peak resident slots: admitted/evicted counters update at
            # the event (the occupancy gauge only publishes every 0.1s)
            peak, pend = 0, set(range(len(futs)))
            while pend:
                pend = {k for k in pend if not futs[k].done()}
                st = eng.stats()
                peak = max(peak, min(int(st.get("admitted", 0))
                                     - int(st.get("evicted", 0)), nslots))
                time.sleep(0.005)
            wall = time.monotonic() - t0
            outs = []
            for f in futs:
                try:
                    outs.append(f.result(1).tolist())
                except Exception:
                    outs.append(None)
            st = eng.stats()
        return {"warm": warm, "wall": wall, "outs": outs, "peak": peak,
                "xla": _XLA_COMPILES[0] - xla0,
                "compiles": st["compile_count"], "stats": st}

    # interleaved best-of-2 walls so a background-noise spike on either
    # run can't decide the throughput comparison
    dense, paged = run(False), run(True)
    d2, p2 = run(False), run(True)
    dense["wall"] = min(dense["wall"], d2["wall"])
    paged["wall"] = min(paged["wall"], p2["wall"])
    total = PAGED_REQS * PAGED_TOKENS
    d_tps, p_tps = total / dense["wall"], total / paged["wall"]
    pst = paged["stats"]
    drafted = int(pst.get("spec_drafted", 0))
    # paged-flash dispatch gate: on this CPU host the engine MUST have
    # used the gather-then-attend fallback (so the bit-identity above is
    # the fallback's correctness proof), while the same geometry on a
    # TPU backend must select the Pallas kernel (ops/paged_attention.py)
    from paddle_tpu.ops.paged_attention import paged_flash_eligible
    hd = 32 // 4  # gate_paged model: hidden 32, 4 heads
    return {
        "flash_fallback_on_cpu": not paged_flash_eligible(hd, PAGE_SIZE),
        "flash_selected_on_tpu": paged_flash_eligible(hd, PAGE_SIZE,
                                                      backend="tpu"),
        "token_identical": bool(paged["outs"] == refs),
        "dense_identical": bool(dense["outs"] == refs),
        "hbm_budget_pages": POOL_PAGES,  # DENSE_SLOTS * CACHE / PAGE_SIZE
        "dense_peak_slots": dense["peak"],
        "paged_peak_slots": paged["peak"],
        "resident_slots_up": bool(paged["peak"] > dense["peak"]),
        "dense_tokens_per_s": round(d_tps, 1),
        "paged_tokens_per_s": round(p_tps, 1),
        "tps_not_worse": bool(p_tps >= d_tps),
        # buckets [32] -> admit + verify step + [B,1] fast step + cow
        "closed_compile_set": (paged["compiles"] == 1 + 3
                               and paged["xla"] == 0),
        "xla_recompiles_post_warmup": paged["xla"],
        "prefix_hits": int(pst.get("prefix_hits", 0)),
        "cow_copies": int(pst.get("cow_copies", 0)),
        "spec_accept_rate": round(
            int(pst.get("spec_accepted", 0)) / drafted, 2) if drafted else 0.0,
        "preempted": int(pst.get("preempted", 0)),
    }


def main():
    t0 = time.time()
    model = _model()
    hol = gate_hol(model)
    probe = gate_router_probe(model)
    paged = gate_paged(_paged_model())
    passed = (hol["token_identical"] and hol["matches_legacy"]
              and hol["closed_compile_set"] and hol["lost"] == 0
              and hol["hol_2x"]
              and probe["routed_identical"]
              and probe["healthy"] == probe["replicas"]
              and probe["probe_failures"] == 0
              and paged["token_identical"] and paged["dense_identical"]
              and paged["resident_slots_up"] and paged["tps_not_worse"]
              and paged["closed_compile_set"]
              and paged["flash_fallback_on_cpu"]
              and paged["flash_selected_on_tpu"])
    print(json.dumps({"pass": bool(passed), "hol": hol, "probe": probe,
                      "paged": paged,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

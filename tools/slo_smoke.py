"""Tracing + SLO gate: end-to-end spans, burn-rate alert, scale signal (CPU).

One-command proof of the request-tracing and SLO-engine contracts over a
live 2-replica continuous-batching router:

1. **Trace completeness + closed compile set** — with tracing enabled, a
   routed generation produces router/submit, router/dispatch,
   batcher/queue, slot/admit, slot/decode and slot/evict spans sharing
   one trace_id in the merged chrome export, with zero post-warmup XLA
   compiles (tracing must not perturb the compile cache).
2. **Burn-rate alert + scale signal** — an injected decode latency fault
   (150 ms per step) burns the p99 latency budget: the SLO engine
   alerts on both windows, analysis rule M903 fires (post-warmup burn),
   and the Router receives a scale-up :class:`ScaleSignal` through
   ``bind_router``.
3. **Off means off** — with tracing disabled, routed traffic records
   nothing (a fresh tracer enabled afterwards has seen zero spans).

Prints one JSON line; exit 0 iff all three gates hold.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.monitoring  # noqa: E402
import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import observability as obs  # noqa: E402
from paddle_tpu.analysis import RetraceMonitor  # noqa: E402
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM  # noqa: E402
from paddle_tpu.observability import tracing  # noqa: E402
from paddle_tpu.observability.slo import Objective, SloEngine  # noqa: E402
from paddle_tpu.resilience import FaultPlan  # noqa: E402
from paddle_tpu.resilience import retry as _retry  # noqa: E402
from paddle_tpu.serving import GenerationEngine, Router  # noqa: E402

BUCKETS = [8, 16]
REQUIRED_SPANS = ("router/submit", "router/dispatch", "batcher/queue",
                  "slot/admit", "slot/decode", "slot/evict")

_XLA_COMPILES = [0]
jax.monitoring.register_event_listener(
    lambda name, **kw: _XLA_COMPILES.__setitem__(0, _XLA_COMPILES[0] + 1)
    if name == "/jax/compilation_cache/compile_requests_use_cache" else None)


def _model():
    pt.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=128, num_layers=2,
                    num_heads=4, max_position=256, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _traffic(router, n=4, tokens=3):
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 97, size=4 + k).astype(np.int32)
               for k in range(n)]
    futs = [router.submit(p, max_new_tokens=tokens) for p in prompts]
    return [f.result(120) for f in futs]


def gate_trace(router, workdir):
    """Full router->slot span tree in the merged chrome export, zero
    post-warmup compiles with tracing on."""
    tracing.enable()
    xla0 = _XLA_COMPILES[0]
    _traffic(router)
    time.sleep(0.3)  # let the engine loops commit the evict spans
    recompiles = _XLA_COMPILES[0] - xla0

    base = os.path.join(workdir, "requests.jsonl")
    out = os.path.join(workdir, "requests.chrome.json")
    tracing.export_jsonl(base, process_index=0)
    n_events = tracing.merge_chrome(base, out)
    with open(out) as f:
        doc = json.load(f)
    by_trace = {}
    for ev in doc["traceEvents"]:
        by_trace.setdefault(ev["args"]["trace_id"], set()).add(ev["name"])
    complete = [tid for tid, names in by_trace.items()
                if all(r in names for r in REQUIRED_SPANS)]
    return {
        "merged_events": n_events,
        "traces": len(by_trace),
        "complete_traces": len(complete),
        "trace_complete": bool(complete),
        "xla_recompiles_post_warmup": recompiles,
        "closed_compile_set": recompiles == 0,
        "tracer": tracing.active().stats(),
    }


def gate_slo(router):
    """Injected decode latency burns the budget: multi-window alert, M903
    after warmup, scale-up signal delivered to the router."""
    obs.enable()
    mon = RetraceMonitor().install()
    eng = SloEngine(
        [Objective.latency("gen_p99", threshold_ms=100.0,
                           engine=router.name, goal=0.99,
                           windows=((8.0, 2.0, 2.0),))])
    eng.install()
    eng.bind_router(router)
    _retry.mark_warm()  # post-warmup burn is what M903 is about
    up0 = router.metrics.snapshot().get("scale_up_signals", 0)
    try:
        with FaultPlan.parse("site=serving.decode,every=1,latency_ms=150"):
            for _ in range(3):
                _traffic(router, n=2)
                eng.tick()
                time.sleep(0.2)
        eng.tick()
        snap = eng.snapshot()
        rules = [d.rule for d in mon.diagnostics()]
        up = router.metrics.snapshot().get("scale_up_signals", 0) - up0
        return {
            "alerts": snap["alerts"],
            "alerts_after_warm": snap["alerts_after_warm"],
            "max_burn": round(snap["max_burn"], 1),
            "alerting": snap["alerting"],
            "m903": "M903" in rules,
            "scale_up_signals": up,
            "scaled_up": up >= 1,
            "last_signal": snap["last_signal"],
        }
    finally:
        eng.close()
        mon.uninstall()
        obs.disable()  # also disables tracing


def gate_off(router):
    """Disabled tracing records nothing — the single-falsy-check hooks
    must be inert."""
    assert tracing.active() is None
    _traffic(router, n=2)
    time.sleep(0.2)
    tr = tracing.enable()  # fresh tracer, after the traffic
    try:
        return {"recorded_while_off": tr.stats()["recorded"],
                "off_means_off": tr.stats()["recorded"] == 0}
    finally:
        tracing.disable()


def main():
    import tempfile

    t0 = time.time()
    model = _model()
    engines = [GenerationEngine(model, prompt_buckets=BUCKETS, batch_size=2,
                                continuous=True, name=f"slo-smoke-g{i}")
               for i in range(2)]
    router = Router(engines, name="slo-smoke-router", probe_interval_s=0.2)
    try:
        router.warmup()
        with tempfile.TemporaryDirectory() as d:
            trace = gate_trace(router, d)
        slo = gate_slo(router)
        off = gate_off(router)
    finally:
        router.close(timeout=30)
    passed = (trace["trace_complete"] and trace["closed_compile_set"]
              and slo["alerts_after_warm"] >= 1 and slo["m903"]
              and slo["scaled_up"] and off["off_means_off"])
    print(json.dumps({"pass": bool(passed), "trace": trace, "slo": slo,
                      "off": off, "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

"""Autoscaling-loop + disaggregation gate: deterministic traffic chaos (CPU).

One-command proof that the closed autoscaling loop and prefill/decode
disaggregation hold their invariants under seeded open-loop traffic —
router + ``SloEngine`` + ``ReplicaPool`` driven together through
``serving.scenarios``:

1. **Lifecycle** — a flash crowd burns the latency budget, the SLO
   engine signals up, the :class:`ReplicaPool` cold-starts warmed
   replicas through the half-open admit path; the quiet tail scales back
   down.  Gates: the fleet scales up AND down inside its
   ``min..max`` bounds, zero thrash (rule S605 stays silent), zero
   accepted requests lost across four scenarios (flash crowd, diurnal,
   heavy tail, poison), every poison request cleanly rejected, no alert
   left burning at the end, and zero post-warmup XLA compiles outside
   pool cold-start windows — per-engine compile sets stay closed.
2. **Disaggregation** — the same prefill-heavy burst scenario replayed
   against a 2-replica co-located fleet and a 1+1
   prefill/decode-disaggregated fleet: decode-class (short-prompt) p99
   must be strictly better disaggregated, with bit-identical tokens
   request-for-request.

Prints one JSON line; exit 0 iff both gates hold.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.monitoring  # noqa: E402
import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import observability as obs  # noqa: E402
from paddle_tpu.analysis import RetraceMonitor  # noqa: E402
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM  # noqa: E402
from paddle_tpu.observability.slo import Objective, SloEngine  # noqa: E402
from paddle_tpu.serving import (DisaggServer, GenerationEngine,  # noqa: E402
                                ReplicaPool, Router, diurnal, flash_crowd,
                                heavy_tail, poison, run_scenario)

_XLA_COMPILES = [0]
jax.monitoring.register_event_listener(
    lambda name, **kw: _XLA_COMPILES.__setitem__(0, _XLA_COMPILES[0] + 1)
    if name == "/jax/compilation_cache/compile_requests_use_cache" else None)


def _model():
    pt.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=128, num_layers=2,
                    num_heads=4, max_position=256, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _p99(values):
    return float(np.percentile(np.asarray(values, np.float64), 99))


def gate_lifecycle():
    """Flash crowd -> scale up through warm+probe admission; quiet tail
    -> drain-based scale down; four chaos scenarios with zero loss and a
    closed post-warmup compile set."""
    model = _model()
    made = []  # every engine the fleet ever ran, for the compile audit

    def factory():
        eng = GenerationEngine(model, prompt_buckets=[8, 16], batch_size=2,
                               continuous=True, paged=True, kv_page_size=16,
                               name=f"scn-g{len(made)}")
        made.append(eng)
        return eng

    router = Router([factory()], name="scn-router")
    obs.enable()
    mon = RetraceMonitor().install()
    slo = SloEngine(
        [Objective.latency("gen_p99", threshold_ms=20.0,
                           engine=router.name, goal=0.9,
                           windows=((4.0, 1.0, 1.5),))],
        scale_down_burn=0.2)
    slo.install()
    slo.bind_router(router)
    pool = ReplicaPool(router, factory, min_replicas=1, max_replicas=3,
                       cooldown_s=1.5, up_consecutive=1, down_consecutive=2,
                       thrash_window_s=2.0, drain_timeout_s=30.0,
                       async_actions=False, name="scn-pool")
    warm_compiles = router.warmup()

    fleet_sizes = []
    samples = [(_XLA_COMPILES[0], 0, 0)]

    def tick(_t):
        slo.tick()
        fleet_sizes.append(len(router.replicas))
        snap = pool.stats()
        samples.append((_XLA_COMPILES[0], len(pool.action_spans),
                        snap["actions_inflight"]))

    scenarios = [
        flash_crowd(duration_s=8.0, base_rps=2.0, burst_rps=40.0,
                    burst_at=0.15, burst_frac=0.4, prompt_len=(4, 12),
                    max_new_tokens=(4, 8), burst_max_new_tokens=(16, 24),
                    seed=101),
        diurnal(duration_s=8.0, base_rps=1.0, peak_rps=2.5,
                prompt_len=(4, 12), max_new_tokens=(3, 6), seed=102),
        heavy_tail(duration_s=6.0, rps=2.5, prompt_len=(4, 12),
                   max_budget=16, seed=103),
        poison(duration_s=5.0, rps=4.0, poison_frac=0.3,
               oversize_len=4096, prompt_len=(4, 12),
               max_new_tokens=(3, 6), seed=104),
    ]
    try:
        reports = [run_scenario(router, s, tick=tick, tick_s=0.5,
                                result_timeout_s=120.0) for s in scenarios]
    finally:
        final = slo.snapshot()
        rules = [d.rule for d in mon.diagnostics()]
        pstats = pool.stats()
        pool.close()
        slo.close()
        mon.uninstall()
        obs.disable()
        router.close(timeout=30)

    # XLA attribution: between consecutive ticks where NO pool action
    # started, finished, or was in flight, the process must not compile —
    # serving replicas run a closed set; only cold-start windows compile.
    unattributed = 0
    for (c0, s0, i0), (c1, s1, i1) in zip(samples, samples[1:]):
        if s0 == s1 and i0 == 0 and i1 == 0 and c1 != c0:
            unattributed += c1 - c0
    # per-engine audit: every engine the fleet ever ran still has exactly
    # its warmup-time compile count (buckets + 3 paged executables, +0 for
    # the default role)
    per_engine = {e.name: e.compile_count for e in made}
    engines_closed = all(c == len([8, 16]) + 3 for c in per_engine.values())

    n_poison = sum(1 for ev in scenarios[3].events if ev.poison)
    return {
        "reports": [{k: v for k, v in r.items() if k != "records"}
                    for r in reports],
        "warm_compiles": warm_compiles,
        "scale_ups": pstats["scale_ups"],
        "scale_downs": pstats["scale_downs"],
        "scaled_up_and_down": (pstats["scale_ups"] >= 1
                               and pstats["scale_downs"] >= 1),
        "fleet_min": min(fleet_sizes),
        "fleet_max": max(fleet_sizes),
        "bounded": 1 <= min(fleet_sizes) and max(fleet_sizes) <= 3,
        "thrash_after_warm": pstats["thrash_events_after_warm"],
        "s605_silent": "S605" not in rules,
        "stale_signals": pstats["stale_signals"],
        "lost": sum(r["lost"] for r in reports),
        "failed": sum(r["failed"] for r in reports),
        "zero_loss": all(r["lost"] == 0 and r["failed"] == 0
                         for r in reports),
        "poison_events": n_poison,
        "poison_rejected": reports[3]["rejected"],
        "poison_clean": (reports[3]["rejected"] == n_poison
                         and reports[3]["poison_accepted"] == 0),
        "alerting_at_end": final.get("alerting", []),
        "budget_recovered": not final.get("alerting"),
        "unattributed_compiles": unattributed,
        "per_engine_compiles": per_engine,
        "compile_set_closed": engines_closed and unattributed == 0,
        "pool": pstats,
    }


def gate_disagg():
    """One prefill-heavy burst scenario, two fleet layouts, same total
    replica count: decode-class p99 must be strictly better
    disaggregated, tokens bit-identical request-for-request."""
    model = _model()
    buckets = [8, 64]

    def eng(role, name):
        return GenerationEngine(model, prompt_buckets=buckets, batch_size=2,
                                continuous=True, paged=True, kv_page_size=16,
                                role=role, name=name)

    # decode-class victims: short prompts with LONG budgets, arriving
    # before and through a heavy burst of long-prompt/1-2-token requests
    # — pure prefill pressure.  Co-located, every burst admission runs a
    # 64-bucket forward between the victims' decode steps; disaggregated,
    # victims decode on a replica that only ever adopts pages.
    scenario = flash_crowd(
        duration_s=8.0, base_rps=3.0, burst_rps=60.0, burst_at=0.25,
        burst_frac=0.35, prompt_len=(4, 8), burst_prompt_len=(48, 64),
        max_new_tokens=(48, 64), burst_max_new_tokens=(1, 2), seed=211)

    colo = Router([eng("any", "colo-g0"), eng("any", "colo-g1")],
                  name="colo-rt")
    colo.warmup()
    try:
        colo_report = run_scenario(colo, scenario, result_timeout_s=120.0)
    finally:
        colo.close(timeout=30)

    disagg = DisaggServer(eng("prefill", "dis-pre"),
                          eng("decode", "dis-dec"), name="dis")
    disagg.warmup()
    try:
        dis_report = run_scenario(disagg, scenario, result_timeout_s=120.0)
    finally:
        disagg.close(timeout=30)

    def decode_class(report):
        return [r["latency_ms"] for r in report["records"]
                if r["ok"] and r["prompt_len"] <= 8]

    colo_p99 = _p99(decode_class(colo_report))
    dis_p99 = _p99(decode_class(dis_report))
    identical = (
        colo_report["completed"] == dis_report["completed"]
        and all(a["tokens"] == b["tokens"]
                for a, b in zip(colo_report["records"],
                                dis_report["records"])))
    return {
        "colo": {k: v for k, v in colo_report.items() if k != "records"},
        "disagg": {k: v for k, v in dis_report.items() if k != "records"},
        "colo_decode_p99_ms": round(colo_p99, 1),
        "disagg_decode_p99_ms": round(dis_p99, 1),
        "decode_p99_improved": dis_p99 < colo_p99,
        "zero_loss": (colo_report["lost"] == 0 and dis_report["lost"] == 0
                      and colo_report["failed"] == 0
                      and dis_report["failed"] == 0),
        "tokens_identical": identical,
    }


def main():
    t0 = time.time()
    life = gate_lifecycle()
    dis = gate_disagg()
    passed = (life["scaled_up_and_down"] and life["bounded"]
              and life["s605_silent"] and life["thrash_after_warm"] == 0
              and life["zero_loss"] and life["poison_clean"]
              and life["budget_recovered"] and life["compile_set_closed"]
              and dis["decode_p99_improved"] and dis["zero_loss"]
              and dis["tokens_identical"])
    print(json.dumps({"pass": bool(passed), "lifecycle": life,
                      "disagg": dis,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

"""Quantized-serving gate: int8/fp8 numerics, HBM economics, hot swap (CPU).

One-command proof of the quantized serving path's contracts, cheap enough
for every gate run:

1. **Token agreement with margin accounting** — int8 and fp8 engines
   decode a seeded workload; every emitted token is teacher-forced
   through the fp32 model and through a weight-quantized clone.  Steps
   where fp32's greedy margin (top1 - top2 logit gap) exceeds
   ``MARGIN_K`` x the measured quantized-logit perturbation must agree
   EXACTLY — a quantized engine may flip genuine near-ties, never a
   clear-margin decision.  Overall agreement is reported and floored.
2. **Equal-HBM resident slots** — a float paged engine and an int8-KV
   paged engine run the same workload on EQUAL pool bytes (int8 pages +
   their fp32 scale planes must measure <= the float pool's bytes, from
   the live arrays): the int8 engine must hold STRICTLY more peak
   resident decode slots, and its tokens/s must be at or above the
   float baseline (interleaved best-of-2 walls).
3. **Quantized rolling swap, zero compiles** — a :class:`Router` over
   two int8 engines hot-swaps a ``slim.export_quantized`` artifact via
   ``swap_weights_rolling`` under the XLA compile-event listener: zero
   post-warmup compile events across drain + swap + re-probe + serve,
   and the served tokens actually change (the swap took).

Prints one JSON line; exit 0 iff all three gates hold.
"""
import copy
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.monitoring  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import slim  # noqa: E402
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM  # noqa: E402
from paddle_tpu.serving import GenerationEngine, Router  # noqa: E402

# dispatch-dominated CPU geometry (hidden 32, hd 8): the regime serving
# decode actually lives in, where the int8 path's smaller KV gathers and
# MXU-shaped matmuls are pure win rather than a FLOP tradeoff
CACHE = 64
PAGE = 16
SLOTS = 4
NTOK = 16
REQS = 8
# equal-HBM pool sizing: per token-head the float pool stores hd*4 bytes,
# the int8 pool hd*1 + 4 (scale plane) — at hd=8 that is 32 vs 12 bytes,
# so 8 float pages buy 21 int8 pages in the same budget (asserted from
# the live arrays, not this comment)
F32_PAGES = 8
INT8_PAGES = 21
# a clear-margin flip is a quantization bug, not noise: the fp32 margin
# must exceed MARGIN_K x the measured teacher-forced logit perturbation
# before a disagreement counts against the gate (and at least one served
# token must clear the bar, or the check would be vacuous)
MARGIN_K = 4.0
AGREE_FLOOR = 0.85

_XLA_COMPILES = [0]
jax.monitoring.register_event_listener(
    lambda name, **kw: _XLA_COMPILES.__setitem__(0, _XLA_COMPILES[0] + 1)
    if name == "/jax/compilation_cache/compile_requests_use_cache" else None)


def _model(seed=13):
    pt.seed(seed)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_position=CACHE, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _prompts(rng, n, lo, hi):
    return [rng.randint(1, 97, size=lo + (k % (hi - lo))).astype(np.int32)
            for k in range(n)]


def _run_engine(model, quantized, pages, prompts, name):
    """Serve the workload; return (best_wall_s, outputs, peak_slots)."""
    eng = GenerationEngine(model, prompt_buckets=[48], batch_size=SLOTS,
                           cache_len=CACHE, continuous=True, paged=True,
                           kv_pages=pages, kv_page_size=PAGE,
                           speculative_k=0, quantized=quantized, name=name)
    with eng:
        eng.warmup()
        t0 = time.monotonic()
        futs = [eng.submit(p, NTOK) for p in prompts]
        peak, pend = 0, set(range(len(futs)))
        while pend:
            pend = {k for k in pend if not futs[k].done()}
            st = eng.stats()
            peak = max(peak, min(int(st.get("admitted", 0))
                                 - int(st.get("evicted", 0)), SLOTS))
            time.sleep(0.002)
        wall = time.monotonic() - t0
        outs = [f.result(1).tolist() for f in futs]
    return wall, outs, peak


def gate_agreement(model):
    """Quantized engines may flip near-ties, never clear-margin tokens."""
    rng = np.random.RandomState(7)
    prompts = _prompts(rng, REQS, 17, 26)

    def logits_at(m, hist):
        return np.asarray(m(jnp.asarray([hist], jnp.int32)))[0, -1]

    out = {}
    for mode in ("int8", "fp8"):
        _, outs, _ = _run_engine(model, mode, INT8_PAGES, prompts,
                                 f"quant-smoke-{mode}")
        qm = copy.deepcopy(model)
        slim.quantize_weights(qm, mode)
        # pass 1: the actual quantized-logit perturbation on the served
        # histories — the noise floor the margin filter calibrates to
        steps = []
        delta = 0.0
        for p, toks in zip(prompts, outs):
            hist = [int(x) for x in p]
            for t in toks:
                lf = logits_at(model, hist)
                delta = max(delta, float(np.max(np.abs(
                    logits_at(qm, hist) - lf))))
                steps.append((lf, int(t)))
                hist.append(int(t))
        # pass 2: margin accounting against the calibrated floor
        tau = MARGIN_K * delta
        total = agree = clear = clear_flips = 0
        for lf, t in steps:
            order = np.argsort(lf)
            margin = float(lf[order[-1]] - lf[order[-2]])
            ok = int(np.argmax(lf)) == t
            total += 1
            agree += int(ok)
            if margin > tau:
                clear += 1
                clear_flips += int(not ok)
        out[mode] = {
            "tokens": total,
            "agreement": round(agree / total, 3),
            "logit_delta": round(delta, 4),
            "margin_tau": round(tau, 4),
            "clear_margin_tokens": clear,
            "clear_margin_flips": clear_flips,
            "ok": bool(clear_flips == 0 and clear > 0
                       and agree / total >= AGREE_FLOOR),
        }
    out["ok"] = bool(out["int8"]["ok"] and out["fp8"]["ok"])
    return out


def gate_hbm(model):
    """Strictly more resident slots + tokens/s >= float, at equal bytes."""
    gpt = model.gpt

    def pool_bytes(pages, dtype=None):
        cache = gpt.init_paged_cache(pages, PAGE, dtype=dtype)
        return sum(int(t.nbytes) for layer in cache["layers"]
                   for t in layer.values())

    f32_bytes = pool_bytes(F32_PAGES)
    int8_bytes = pool_bytes(INT8_PAGES, dtype=jnp.int8)

    rng = np.random.RandomState(17)
    # 3-page prompts: the float pool admits 2 slots (3 pages each, 8
    # total), the int8 pool all 4 — same bytes, double the residency
    prompts = _prompts(rng, REQS, 36, 44)
    wf, outs_f, peak_f = _run_engine(model, None, F32_PAGES, prompts,
                                     "quant-smoke-f32")
    wq, outs_q, peak_q = _run_engine(model, "int8", INT8_PAGES, prompts,
                                     "quant-smoke-i8")
    # interleaved best-of-2 walls: background noise can't pick the winner
    wf2, _, pf2 = _run_engine(model, None, F32_PAGES, prompts,
                              "quant-smoke-f32b")
    wq2, _, pq2 = _run_engine(model, "int8", INT8_PAGES, prompts,
                              "quant-smoke-i8b")
    wf, wq = min(wf, wf2), min(wq, wq2)
    peak_f, peak_q = max(peak_f, pf2), max(peak_q, pq2)
    total = REQS * NTOK
    f_tps, q_tps = total / wf, total / wq
    return {
        "f32_pool_bytes": f32_bytes,
        "int8_pool_bytes": int8_bytes,
        "equal_hbm": bool(int8_bytes <= f32_bytes),
        "f32_pages": F32_PAGES,
        "int8_pages": INT8_PAGES,
        "f32_peak_slots": peak_f,
        "int8_peak_slots": peak_q,
        "resident_slots_up": bool(peak_q > peak_f),
        "f32_tokens_per_s": round(f_tps, 1),
        "int8_tokens_per_s": round(q_tps, 1),
        "tps_not_worse": bool(q_tps >= f_tps),
        "lost": sum(o is None for o in outs_f + outs_q),
        "ok": bool(int8_bytes <= f32_bytes and peak_q > peak_f
                   and q_tps >= f_tps),
    }


def _count_eqns(jaxpr, pred):
    n = 0
    for eqn in jaxpr.eqns:
        n += int(pred(eqn))
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None:
                    n += _count_eqns(inner, pred)
    return n


def gate_flash_dispatch(model):
    """The quantized paged decode's kernel-dispatch contract: on this CPU
    host the engine keeps the gather-then-attend fallback (whose output
    the agreement gate above scores), the same geometry on a TPU backend
    selects the Pallas paged-flash kernel, and that kernel's dispatch
    graph contains NO pool-sized int8→float conversion — pages are
    dequantized per-block inside the kernel, so the quantized pool's
    HBM-byte advantage (gate_hbm) survives the attention read."""
    from paddle_tpu.ops.paged_attention import paged_flash_decode
    from paddle_tpu.ops.paged_attention import paged_flash_eligible

    cfg = model.gpt.cfg
    hd = cfg.hidden_size // cfg.num_heads
    H, P = cfg.num_heads, INT8_PAGES
    rng = np.random.RandomState(23)
    q = jnp.asarray(rng.randn(SLOTS, H, 1, hd), jnp.float32)
    pool = jnp.asarray(rng.randint(-127, 128, (P + 1, H, PAGE, hd)),
                       jnp.int8)
    scale = jnp.asarray(rng.rand(P + 1, H, PAGE), jnp.float32)
    tables = jnp.zeros((SLOTS, CACHE // PAGE), jnp.int32)
    mask = jnp.ones((SLOTS, 1, CACHE), bool)
    jaxpr = jax.make_jaxpr(
        lambda *a: paged_flash_decode(*a, block_h=1))(
            q, pool, pool, tables, mask, scale, scale)
    pool_shape = tuple(pool.shape)
    full_dequants = _count_eqns(
        jaxpr.jaxpr,
        lambda e: (e.primitive.name == "convert_element_type"
                   and tuple(getattr(e.invars[0].aval, "shape", ())) ==
                   pool_shape
                   and str(e.outvars[0].aval.dtype) == "float32"))
    kernel_calls = _count_eqns(
        jaxpr.jaxpr, lambda e: e.primitive.name == "pallas_call")
    return {
        "fallback_on_cpu": not paged_flash_eligible(hd, PAGE),
        "selected_on_tpu": paged_flash_eligible(hd, PAGE, backend="tpu"),
        "kernel_calls_in_graph": kernel_calls,
        "full_pool_float_dequants": full_dequants,
        "ok": bool(not paged_flash_eligible(hd, PAGE)
                   and paged_flash_eligible(hd, PAGE, backend="tpu")
                   and kernel_calls == 1 and full_dequants == 0),
    }


def gate_rolling_swap(model):
    """Quantized rolling swap across a router: zero XLA compile events."""
    donor = _model(seed=29)  # different weights, same tree geometry
    tmp = tempfile.mkdtemp(prefix="quant_smoke_")
    artifact = slim.export_quantized(
        donor, os.path.join(tmp, "donor"), mode="int8")
    rng = np.random.RandomState(23)
    prompts = _prompts(rng, 4, 17, 24)
    engines = [GenerationEngine(model, prompt_buckets=[48], batch_size=2,
                                cache_len=CACHE, continuous=True,
                                paged=True, kv_pages=INT8_PAGES,
                                kv_page_size=PAGE, speculative_k=0,
                                quantized="int8", name=f"quant-smoke-r{i}")
               for i in range(2)]
    router = Router(engines, name="quant-smoke-router",
                    probe_interval_s=60.0)
    try:
        router.warmup()
        before = [router.submit(p, max_new_tokens=4).result(120).tolist()
                  for p in prompts]
        xla0 = _XLA_COMPILES[0]
        swapped = router.swap_weights_rolling(artifact, drain_timeout=60.0)
        after = [router.submit(p, max_new_tokens=4).result(120).tolist()
                 for p in prompts]
        xla_events = _XLA_COMPILES[0] - xla0
        manifest = json.load(open(artifact + ".manifest.json"))
        return {
            "replicas_swapped": swapped,
            "xla_compiles_across_swap": xla_events,
            "weights_took": bool(before != after),
            "manifest_quantization": manifest["quantization"],
            "healthy_after": router.healthy_count(),
            "ok": bool(swapped == 2 and xla_events == 0
                       and before != after
                       and router.healthy_count() == 2),
        }
    finally:
        router.close(timeout=30)


def main():
    t0 = time.time()
    model = _model()
    agreement = gate_agreement(model)
    hbm = gate_hbm(model)
    flash = gate_flash_dispatch(model)
    swap = gate_rolling_swap(model)
    passed = (agreement["ok"] and hbm["ok"] and flash["ok"]
              and swap["ok"])
    print(json.dumps({"pass": bool(passed), "agreement": agreement,
                      "hbm": hbm, "flash_dispatch": flash,
                      "rolling_swap": swap,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Measured-search gate: plan + serving spaces end-to-end, on CPU.

One-command proof of the ``paddle_tpu.tuning`` contracts, the
plan/serving twin of ``kernel_smoke.py``:

1. **Cold process** — with a fresh cache file, a sharding-plan search
   times REAL fused train steps (``Executor.run_steps`` on a tiny MLP
   program) per candidate, and a serving-config search replays the
   SAME deterministic fixed-seed request trace ``bench.py`` uses
   against a real ``GenerationEngine`` per candidate under a p99
   budget.  Both winners persist to disk (schema v2, space-tagged),
   and — because the hand-set default is always in the running — the
   winner's measured score is no worse than the default's in the same
   search (tokens/s for serving, step time for the plan).
2. **Warm process** — a second, separate process over the same cache
   file resolves BOTH configs as pure disk hits with ZERO measured
   searches (the measure callbacks are rigged to explode if invoked),
   builds the tuned serving engine via ``from_tuned``, replays the
   trace after ``mark_warm()`` with K701 silent — then INJECTS a
   fresh post-warm search and requires K701 to fire, proving the
   detector still has teeth.

Prints one JSON line; exit 0 iff every phase holds.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

_COMMON = """
import json, sys

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import GenerationEngine
from paddle_tpu.static.graph import reset_default_programs
from paddle_tpu.tuning import (RequestTrace, engine, plan_space, replay,
                               serving_space)

N_STEPS = 4
PLAN_SHAPES = {"fc1.weight": (16, 32), "fc1.bias": (32,),
               "fc2.weight": (32, 1), "fc2.bias": (1,)}
BASE_SERVING = {"buckets": [16, 48], "batch_size": 8,
                "max_queue_delay_ms": 1.0}
TRACE = RequestTrace.synthetic(n=16)
BUDGET_MS = 120000.0  # generous on CPU: the budget MACHINERY is under test


def build_train():
    paddle.seed(0)
    reset_default_programs()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [-1, 16])
        y = fluid.data("y", [-1, 1])
        h = fluid.layers.fc(x, 32, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    return exe, main, loss


def plan_measure_factory():
    from paddle_tpu.distributed.fleet import DistributedStrategy

    exe, main, loss = build_train()
    rng = np.random.RandomState(0)
    X = rng.rand(N_STEPS, 8, 16).astype(np.float32)
    Y = rng.rand(N_STEPS, 8, 1).astype(np.float32)

    def run_step(config):
        # apply the candidate's collective dials, then run REAL fused
        # train steps — what run_steps returns is what gets timed
        plan_space.apply_plan(config, strategy=DistributedStrategy())
        return exe.run_steps(main, feed={"x": X, "y": Y},
                             fetch_list=[loss], iterations=N_STEPS)

    return plan_space.make_step_measure(run_step, repeats=2)


def build_model():
    paddle.seed(1234)
    cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                    num_heads=2, max_position=128, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m
"""

_COLD = _COMMON + """
pd, sd, results = {}, {}, {}
plan_won = plan_space.tune_plan(
    "gate-plan", shapes=PLAN_SHAPES, measure=plan_measure_factory(),
    details=pd)

model = build_model()
factory = lambda cfg: GenerationEngine.from_tuned(model, cfg)
serve_won = serving_space.tune_serving(
    "gate-serve", BASE_SERVING, trace=TRACE, factory=factory,
    latency_budget_ms=BUDGET_MS,
    sweeps={"batch_size": (4, 16), "max_queue_delay_ms": (0.5,)},
    results=results, details=sd)

print(json.dumps({"counters": engine.get_counters(),
                  "plan": {"won": plan_won, "details": pd},
                  "serve": {"won": serve_won, "details": sd},
                  "cache_path": engine.cache_path()}))
"""

_WARM = _COMMON + """
from paddle_tpu.analysis import RetraceMonitor

boom = lambda cfg: (_ for _ in ()).throw(
    AssertionError("measured search ran in the warm process"))

with RetraceMonitor() as mon:
    plan_won = plan_space.tune_plan("gate-plan", shapes=PLAN_SHAPES,
                                    measure=boom)
    serve_won = serving_space.tune_serving("gate-serve", BASE_SERVING,
                                           trace=TRACE, measure=boom)
    # serve live traffic on the tuned config: warmup closes the compile
    # set and marks warm; the replayed trace must hit only cached configs
    model = build_model()
    with GenerationEngine.from_tuned(model, serve_won,
                                     name="tuned-replay") as eng:
        eng.warmup()
        stats = replay(eng, TRACE)
    k701_clean = [d for d in mon.diagnostics() if d.rule == "K701"]

# inject a post-warm search: K701 must fire for the serving space
with RetraceMonitor() as mon2:
    engine.mark_warm()
    serving_space.tune_serving("gate-serve-injected", BASE_SERVING,
                               trace=TRACE, measure=lambda cfg: 1.0)
    k701_injected = [d.message for d in mon2.diagnostics()
                     if d.rule == "K701"]

print(json.dumps({"counters": engine.get_counters(),
                  "plan_won": plan_won, "serve_won": serve_won,
                  "replay": stats,
                  "k701_clean": [d.message for d in k701_clean],
                  "k701_injected": k701_injected}))
"""


def _run_child(code, cache_file):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               FLAGS_measured_search="on",
               FLAGS_kernel_tuning_cache=cache_file)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout + proc.stderr)
        raise SystemExit(f"tune_smoke child failed (rc={proc.returncode})")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main():
    t0 = time.time()
    fd, cache_file = tempfile.mkstemp(suffix=".json", prefix="tune_")
    os.close(fd)
    os.unlink(cache_file)  # children create it; start truly cold
    try:
        cold = _run_child(_COLD, cache_file)
        warm = _run_child(_WARM, cache_file)
        entries = json.load(open(cache_file)).get("entries", {})
    finally:
        if os.path.exists(cache_file):
            os.unlink(cache_file)

    cc, wc = cold["counters"], warm["counters"]
    pd = cold["plan"]["details"]
    sd = cold["serve"]["details"]
    spaces = sorted(e.get("space") for e in entries.values()
                    if e.get("name") in ("gate-plan", "gate-serve"))
    checks = {
        # cold: both spaces ran a real measured search and persisted
        "cold_plan_search": cc.get("gate-plan", {}).get("searches") == 1,
        "cold_serve_search": cc.get("gate-serve", {}).get("searches") == 1,
        "cold_plan_timed": pd.get("n_timed", 0) >= 2,
        "cold_serve_timed": sd.get("n_timed", 0) >= 2,
        "cache_both_spaces": spaces == ["plan", "serving"],
        "cache_schema_v2": all(e.get("version") == 2
                               for e in entries.values()),
        # winner no worse than the hand-set default IN THE SAME SEARCH
        # (the default is always a candidate, so this is measured, not
        # assumed: step ms for the plan, ms/token for serving)
        "plan_winner_no_worse": (pd.get("default_ms") is not None
                                 and pd["best_ms"] <= pd["default_ms"]),
        "serve_winner_no_worse": (sd.get("default_ms") is not None
                                  and sd["best_ms"] <= sd["default_ms"]),
        # warm: pure disk hits, zero measured searches, same winners
        "warm_zero_searches": all(
            wc.get(k, {}).get("searches", 0) == 0
            and wc.get(k, {}).get("configs_timed", 0) == 0
            for k in ("gate-plan", "gate-serve")),
        "warm_disk_hits": all(
            wc.get(k, {}).get("disk_hits") == 1
            for k in ("gate-plan", "gate-serve")),
        "winners_stable": (warm["plan_won"] == cold["plan"]["won"]
                           and warm["serve_won"] == cold["serve"]["won"]),
        # tuned engine actually serves the trace, p99 inside the budget
        "replay_tokens": warm["replay"]["tokens"] > 0,
        "replay_p99_in_budget": warm["replay"]["p99_ms"] <= 120000.0,
        # K701: silent on post-warm cache hits, fires on an injected
        # post-warm serving search
        "k701_clean_on_hits": warm["k701_clean"] == [],
        "k701_fires_injected": any(
            "gate-serve-injected" in m and "serving config" in m
            for m in warm["k701_injected"]),
    }
    ok = all(checks.values())
    print(json.dumps({
        "gate": "tune_smoke", "ok": ok, "checks": checks,
        "plan_won": cold["plan"]["won"],
        "serve_won": cold["serve"]["won"],
        "plan_ms": {"best": pd.get("best_ms"),
                    "default": pd.get("default_ms")},
        "serve_ms_per_tok": {"best": sd.get("best_ms"),
                             "default": sd.get("default_ms")},
        "replay": warm.get("replay"),
        "cache_entries": len(entries),
        "seconds": round(time.time() - t0, 1)}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Vocab-independence benchmark for the SelectedRows sparse-embedding path.

VERDICT r3 done-criterion for the sparse path: "a 10M x 64 table trains
with per-step time independent of vocab size".  This prints per-step train
time for Embedding(sparse=True) + Adam(lazy_mode=True) across vocab sizes,
plus the dense path at small vocabs for contrast (dense scales O(vocab):
the backward materializes a table-shaped cotangent and dense Adam rewrites
every moment row).

Run anywhere (CPU or TPU):  python tools/bench_sparse_embedding.py
Reference capability matched: selected_rows.h:41 + fluid/optimizer.py:2026.

Measured on the 1-core CPU dev box (2026-07-31, suite idle; compute-
dominated, so the asymptotics show directly):
    vocab=  100,000  sparse+lazy    6.5 ms
    vocab=1,000,000  sparse+lazy    5.9 ms
    vocab=10,000,000 sparse+lazy    6.8 ms     <- flat
    vocab=  100,000  dense         44.1 ms
    vocab=1,000,000  dense        934.8 ms     <- linear in vocab
On the real v5e chip behind the shared tunnel the ~110 ms per-step
dispatch RTT floors every configuration (sparse 116/116/144 ms at
100k/1M/10M — ratio 1.25, still passing; a local-host TPU run would
mirror the CPU asymptotics without the RTT floor).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def step_time(vocab, sparse, lazy, dim=64, B=256, F=4, iters=20):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu import optimizer as popt

    paddle.seed(0)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(vocab, dim, sparse=sparse)
            self.fc = nn.Linear(dim, 1)

        def forward(self, ids):
            return self.fc(self.emb(ids).mean(axis=1))

    net = Net()
    model = paddle.Model(net, inputs=["ids"], labels=["y"])
    model.prepare(optimizer=popt.Adam(learning_rate=0.01, lazy_mode=lazy),
                  loss=lambda o, y: ((o - y) ** 2).mean())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (B, F)).astype(np.int32)
    y = rng.randn(B, 1).astype(np.float32)
    model.train_batch([ids], [y])  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        model.train_batch([ids], [y])
    jax.block_until_ready(net.emb.weight.value)
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    rows = []
    for vocab in (10**5, 10**6, 10**7):
        ms = step_time(vocab, sparse=True, lazy=True)
        rows.append({"vocab": vocab, "path": "sparse_lazy", "ms": round(ms, 2)})
        print(json.dumps(rows[-1]), flush=True)
    for vocab in (10**5, 10**6):  # dense at 10M would take ~10s/step
        ms = step_time(vocab, sparse=False, lazy=False)
        rows.append({"vocab": vocab, "path": "dense", "ms": round(ms, 2)})
        print(json.dumps(rows[-1]), flush=True)
    sp = [r["ms"] for r in rows if r["path"] == "sparse_lazy"]
    print(json.dumps({
        "metric": "sparse_embedding_step_vocab_independence",
        "value": round(max(sp) / min(sp), 2),
        "unit": "max/min step-time ratio across 100x vocab",
        "pass": max(sp) / min(sp) < 2.0,
    }))


if __name__ == "__main__":
    main()

"""Vocab-independence benchmark for the SelectedRows sparse-embedding path.

VERDICT r3 done-criterion for the sparse path: "a 10M x 64 table trains
with per-step time independent of vocab size".  This prints per-step train
time for Embedding(sparse=True) + Adam(lazy_mode=True) across vocab sizes,
plus the dense path at small vocabs for contrast (dense scales O(vocab):
the backward materializes a table-shaped cotangent and dense Adam rewrites
every moment row).

Run anywhere (CPU or TPU):  python tools/bench_sparse_embedding.py
Reference capability matched: selected_rows.h:41 + fluid/optimizer.py:2026.

Measured on the 1-core CPU dev box (2026-07-31, suite idle; compute-
dominated, so the asymptotics show directly):
    vocab=  100,000  sparse+lazy    6.5 ms
    vocab=1,000,000  sparse+lazy    5.9 ms
    vocab=10,000,000 sparse+lazy    6.8 ms     <- flat
    vocab=  100,000  dense         44.1 ms
    vocab=1,000,000  dense        934.8 ms     <- linear in vocab
On the real v5e chip behind the shared tunnel the ~110 ms per-step
dispatch RTT floors every configuration (sparse 116/116/144 ms at
100k/1M/10M — ratio 1.25, still passing; a local-host TPU run would
mirror the CPU asymptotics without the RTT floor).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def step_time(vocab, sparse, lazy, dim=64, B=256, F=4, iters=20):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu import optimizer as popt

    paddle.seed(0)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(vocab, dim, sparse=sparse)
            self.fc = nn.Linear(dim, 1)

        def forward(self, ids):
            return self.fc(self.emb(ids).mean(axis=1))

    net = Net()
    model = paddle.Model(net, inputs=["ids"], labels=["y"])
    model.prepare(optimizer=popt.Adam(learning_rate=0.01, lazy_mode=lazy),
                  loss=lambda o, y: ((o - y) ** 2).mean())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (B, F)).astype(np.int32)
    y = rng.randn(B, 1).astype(np.float32)
    model.train_batch([ids], [y])  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        model.train_batch([ids], [y])
    jax.block_until_ready(net.emb.weight.value)
    return (time.perf_counter() - t0) / iters * 1e3


def host_step_time(vocab, overlap, dim=64, B=256, F=4, iters=20):
    """The beyond-HBM path: HostEmbeddingTable pull → jit step over the
    pulled rows → push row grads.  ``overlap=True`` uses the async verbs
    (prefetch next batch's rows + worker-side D2H/scatter — the reference
    async communicator's job, communicator.h:268)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer as popt
    from paddle_tpu.incubate import HostEmbeddingTable
    from paddle_tpu.nn.layer_base import functional_call

    paddle.seed(0)
    host = HostEmbeddingTable(vocab, dim, optimizer="adam",
                              learning_rate=0.01, seed=1)
    fc = nn.Linear(dim, 1)
    params = {k: v.value for k, v in fc.named_parameters()}
    opt = popt.Adam(learning_rate=0.01)
    opt_state = opt.init(params)

    @jax.jit
    def jstep(params, opt_state, rows, y):
        def loss_fn(p, r):
            out = functional_call(fc, p, r.mean(axis=1))
            return ((out - y) ** 2).mean()

        loss, (gp, grows) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(params, rows)
        new_p, new_s = opt.update(gp, opt_state, params, lr=0.01)
        return loss, new_p, new_s, grows

    rng = np.random.RandomState(0)
    batches = [(rng.randint(0, vocab, (B, F)).astype(np.int64),
                jnp.asarray(rng.randn(B, 1).astype(np.float32)))
               for _ in range(iters + 1)]
    # compile
    rows0 = jnp.asarray(host.pull(batches[0][0]))
    jstep(params, opt_state, rows0, batches[0][1])

    t0 = time.perf_counter()
    if overlap:
        fut = host.pull_async(batches[0][0])
        for t in range(iters):
            ids, y = batches[t]
            rows = jnp.asarray(fut.result())
            fut = host.pull_async(batches[t + 1][0])  # overlaps the step
            loss, params, opt_state, grows = jstep(params, opt_state,
                                                   rows, y)
            host.push_async(ids, grows)  # D2H on the worker
        host.flush()
    else:
        for t in range(iters):
            ids, y = batches[t]
            rows = jnp.asarray(host.pull(ids))
            loss, params, opt_state, grows = jstep(params, opt_state,
                                                   rows, y)
            host.push(ids, np.asarray(grows))
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / iters * 1e3
    host.close()
    return dt


def main():
    rows = []
    for vocab in (10**5, 10**6, 10**7):
        ms = step_time(vocab, sparse=True, lazy=True)
        rows.append({"vocab": vocab, "path": "sparse_lazy", "ms": round(ms, 2)})
        print(json.dumps(rows[-1]), flush=True)
    for vocab in (10**5, 10**6):  # dense at 10M would take ~10s/step
        ms = step_time(vocab, sparse=False, lazy=False)
        rows.append({"vocab": vocab, "path": "dense", "ms": round(ms, 2)})
        print(json.dumps(rows[-1]), flush=True)
    for vocab in (10**6,):
        ms_sync = host_step_time(vocab, overlap=False)
        ms_async = host_step_time(vocab, overlap=True)
        rows.append({"vocab": vocab, "path": "host_sync",
                     "ms": round(ms_sync, 2)})
        print(json.dumps(rows[-1]), flush=True)
        rows.append({"vocab": vocab, "path": "host_async",
                     "ms": round(ms_async, 2)})
        print(json.dumps(rows[-1]), flush=True)
    sp = [r["ms"] for r in rows if r["path"] == "sparse_lazy"]
    print(json.dumps({
        "metric": "sparse_embedding_step_vocab_independence",
        "value": round(max(sp) / min(sp), 2),
        "unit": "max/min step-time ratio across 100x vocab",
        "pass": max(sp) / min(sp) < 2.0,
    }))
    dev = [r["ms"] for r in rows
           if r["path"] == "sparse_lazy" and r["vocab"] == 10**6][0]
    ha = [r["ms"] for r in rows if r["path"] == "host_async"][0]
    hs = [r["ms"] for r in rows if r["path"] == "host_sync"][0]
    print(json.dumps({
        "metric": "host_embedding_overlap",
        "value": round(ha / dev, 2),
        "unit": "async-host / on-device-sparse step-time ratio at 1M vocab",
        "sync_ratio": round(hs / dev, 2),
        "pass": ha <= hs * 1.05 and ha / dev < 1.5,
    }))


if __name__ == "__main__":
    main()

"""Quick ResNet-50 throughput probe on the real chip (dev tool, not the gate).

Usage: python tools/bench_resnet_probe.py [batch]
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import optimizer as popt
    from paddle_tpu.vision.models import resnet50

    print("devices:", jax.devices())
    paddle.seed(0)
    net = resnet50().astype("bfloat16")
    opt = popt.Momentum(learning_rate=0.1, momentum=0.9, multi_precision=True,
                        weight_decay=1e-4)
    model = paddle.Model(net, inputs=["image"], labels=["label"])
    model.prepare(optimizer=opt,
                  loss=paddle.nn.CrossEntropyLoss())

    rng = np.random.RandomState(0)
    import ml_dtypes
    imgs = rng.uniform(-1, 1, size=(batch, 3, 224, 224)).astype(
        ml_dtypes.bfloat16)
    labels = rng.randint(0, 1000, size=(batch, 1)).astype(np.int64)

    def step():
        loss, _ = model._train_batch_device([imgs], [labels])
        return loss

    t0 = time.perf_counter()
    loss = step()
    print("compile+1st step:", time.perf_counter() - t0, "s")
    for _ in range(2):
        loss = step()
    print("warm loss:", float(loss))

    for w in range(3):
        t0 = time.perf_counter()
        for _ in range(10):
            loss = step()
        final = float(loss)
        dt = time.perf_counter() - t0
        assert np.isfinite(final)
        print(f"window {w}: {batch * 10 / dt:.1f} img/s ({dt:.3f}s)")


if __name__ == "__main__":
    main()

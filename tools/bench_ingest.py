#!/usr/bin/env python
"""Ingest micro-benchmark: native MultiSlot engine vs a pure-Python loader.

The reference keeps its whole ingest stack in C++ for parse throughput
(framework/data_feed.h MultiSlotDataFeed, ~8k LoC); this measures our
ctypes-bound engine (paddle_tpu/native/ingest.cc) against an equivalent
Python parser on the same MultiSlot files.  Target: >=5x.

    python tools/bench_ingest.py [--rows 200000] [--files 8] [--threads 8]
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def write_files(root, nfiles, rows_per_file, seed=0):
    rng = np.random.RandomState(seed)
    paths = []
    for k in range(nfiles):
        p = os.path.join(root, f"part-{k}.txt")
        with open(p, "w") as f:
            for _ in range(rows_per_file):
                n_ids = rng.randint(1, 9)
                ids = rng.randint(0, 10 ** 12, size=n_ids)
                dense = rng.rand(13)
                f.write(f"{n_ids} " + " ".join(map(str, ids)) + " 13 "
                        + " ".join(f"{v:.6f}" for v in dense)
                        + f" 1 {rng.randint(0, 2)}\n")
        paths.append(p)
    return paths


def python_loader(paths):
    """Faithful pure-Python equivalent: parse + type + pad."""
    ids_rows, dense_rows, labels, lens = [], [], [], []
    for p in paths:
        with open(p) as f:
            for line in f:
                toks = line.split()
                i = 0
                c = int(toks[i]); i += 1
                row = np.zeros(8, np.int64)
                row[:c] = [int(t) for t in toks[i:i + c]]
                i += c
                ids_rows.append(row); lens.append(c)
                c2 = int(toks[i]); i += 1
                dense_rows.append(
                    np.array([float(t) for t in toks[i:i + c2]], np.float32))
                i += c2
                i += 1  # label count (1)
                labels.append(int(toks[i]))
    return (np.stack(ids_rows), np.asarray(lens),
            np.stack(dense_rows), np.asarray(labels))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--files", type=int, default=8)
    ap.add_argument("--threads", type=int, default=8)
    args = ap.parse_args()

    from paddle_tpu.io import MultiSlotInMemoryDataset

    with tempfile.TemporaryDirectory() as td:
        paths = write_files(td, args.files, args.rows // args.files)
        size_mb = sum(os.path.getsize(p) for p in paths) / 1e6

        ds = MultiSlotInMemoryDataset(
            slots=[("ids", "int64", 8), ("dense", "float32", 13),
                   ("label", "int64", 1)])
        ds.set_filelist(paths)
        t0 = time.perf_counter()
        n = ds.load_into_memory(thread_num=args.threads)
        t_native = time.perf_counter() - t0

        t0 = time.perf_counter()
        ref = python_loader(paths)
        t_python = time.perf_counter() - t0
        assert len(ref[0]) == n

        print(f"files: {args.files}  rows: {n}  size: {size_mb:.1f} MB")
        print(f"native ({args.threads} threads): {t_native:.3f}s "
              f"({size_mb / t_native:.0f} MB/s)")
        print(f"python loader:                  {t_python:.3f}s "
              f"({size_mb / t_python:.0f} MB/s)")
        print(f"speedup: {t_python / t_native:.1f}x")


if __name__ == "__main__":
    main()

"""Pod-scale multi-host gate: real process gangs, gang restart, failover (CPU).

One-command proof of the multi-host execution contracts, run on every gate
pass with N >= 2 REAL processes launched through
``python -m paddle_tpu.distributed.launch`` over the file gang transport
(the CPU backend joins the jax.distributed coordinator but refuses
cross-process XLA computations, so the host lane carries the pod
semantics — see distributed/gang.py):

1. **Sharded bit-identity** — a 2-process run training NSHARD data
   shards (``DistributedBatchSampler`` slices, per-shard steps combined
   with the gang's rank-ordered ``mean_trees`` reduction) must produce
   final params BIT-IDENTICAL on every rank and BIT-IDENTICAL to a
   single-process run folding the same shards locally.
2. **SIGKILL → gang restore** — one host of a watched 2-process pod is
   SIGKILLed mid-run; the survivor's watchdog must gang-restart its own
   healthy trainer, the gang must re-form (new generation), negotiate
   the min committed ``AutoCheckpoint`` counter, and finish with params
   bit-identical to the uninterrupted run — with ``gang_restores >= 1``
   and both ranks present in the merged per-process metrics JSONL.
3. **Wedged collective** — with ``FLAGS_collective_timeout_s`` armed and
   a latency fault wedging one rank at the ``gang.collective`` site,
   every LIVE rank must raise ``TransientDeviceError`` within the
   deadline instead of hanging the pod.
4. **Router failover across a host kill** — a Router fronting engines
   served from two OTHER processes (``serving.remote``), with
   ``bind_peer_liveness`` wired to the gang heartbeat: SIGKILL one
   engine host mid-traffic; every accepted request must still complete
   (zero lost) and ``peer_evictions >= 1``.
5. **F803** — an injected gang-restart loop must trip the restart-storm
   breaker (exit 77) and fire analysis rule F803; a healthy watched run
   stays silent.

Prints one JSON line; exit 0 iff every gate holds.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

SELF = os.path.abspath(__file__)
NSHARD = 4   # virtual data shards, fixed across world sizes
ROUNDS = 6   # averaging rounds == checkpoint commits per rank
WEDGE_RC = 41  # wedge-child: TransientDeviceError raised within deadline


# -- trainer (runs inside `python -m paddle_tpu.distributed.launch`) --------

def _shard_batch(shard):
    """Shard ``shard``'s slice of the fixed global dataset, selected the
    way a pod host would: a DistributedBatchSampler ranked by shard."""
    import numpy as np

    from paddle_tpu.io.dataset import TensorDataset
    from paddle_tpu.io.sampler import DistributedBatchSampler

    rng = np.random.RandomState(7)
    x = rng.randn(8 * NSHARD, 4).astype(np.float32)
    y = rng.randint(0, 2, size=(8 * NSHARD,)).astype(np.int64)
    sampler = DistributedBatchSampler(TensorDataset([x, y]), batch_size=8,
                                      num_replicas=NSHARD, rank=shard,
                                      shuffle=False)
    idx = [i for batch in sampler for i in batch]
    return x[idx], y[idx]


def _np_tree(state):
    import numpy as np

    return {k: np.asarray(v) for k, v in state.items()}


def pod_trainer(workdir):
    """Per-host body: each process owns NSHARD/world contiguous shards;
    every round runs one SGD step per owned shard from the shared params,
    gathers all per-shard results over the gang, and takes the
    rank-ordered mean (localsgd with H=1 — a pure function of the round
    params, so any world size folding the same shards in the same order
    is bit-identical).  Checkpoints the averaged params every round;
    resume negotiates the gang-wide min committed counter."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as popt
    from paddle_tpu.distributed import env as denv
    from paddle_tpu.distributed import heartbeat
    from paddle_tpu.distributed.gang import default_gang, mean_trees
    from paddle_tpu.incubate.checkpoint import AutoCheckpoint

    from paddle_tpu.distributed.parallel import GANG_RESTART_EXIT_CODE
    from paddle_tpu.framework.errors import TransientDeviceError

    rank, world = denv.process_index(), denv.process_count()
    with open(os.path.join(workdir, f"pid.p{rank}"), "w") as f:
        f.write(str(os.getpid()))
    assert NSHARD % world == 0, (NSHARD, world)
    gang = default_gang("podsmoke")
    k = NSHARD // world
    shards = list(range(rank * k, (rank + 1) * k))
    batches = {s: _shard_batch(s) for s in shards}

    pt.seed(123)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    model = pt.Model(net, inputs=["x"], labels=["y"])
    model.prepare(optimizer=popt.SGD(learning_rate=5e-2),
                  loss=nn.CrossEntropyLoss())
    acp = AutoCheckpoint(model, os.path.join(workdir, f"ck.p{rank}"),
                         save_steps=1, async_save=False)
    try:
        # gang-consistent resume: hosts may disagree on the newest
        # committed counter after a pod failure — agree on the min,
        # rewind past it
        agreed = gang.min_int(acp.latest_counter())
        meta = acp.resume(at_most=agreed) if agreed > 0 else None
        start = int(meta["global_step"]) if meta else 0
        p = _np_tree(model.network.state_dict())
        slow = float(os.environ.get("POD_SMOKE_SLEEP_S", "0") or 0)
        for _round in range(start, ROUNDS):
            local = []
            for s in shards:
                model.network.set_state_dict(p)
                x, y = batches[s]
                model.train_batch([x], [y])
                local.append((s, _np_tree(model.network.state_dict())))
                heartbeat.maybe_beat()
            pairs = sorted((pair for contrib in gang.all_gather_obj(local)
                            for pair in contrib), key=lambda kv: kv[0])
            p = mean_trees([tree for _, tree in pairs])
            model.network.set_state_dict(p)
            acp.step(0)
            end = time.monotonic() + slow  # widen the parent's kill window
            while time.monotonic() < end:
                heartbeat.maybe_beat()
                time.sleep(0.05)
        acp.close()
        gang.barrier()
    except TransientDeviceError:
        # dead peer or abandoned generation: ask the watchdog for a
        # gang restart — relaunch, rejoin, resume from the agreed counter
        acp.close()
        sys.exit(GANG_RESTART_EXIT_CODE)
    np.savez(os.path.join(workdir, f"out.p{rank}.npz"), **p)
    return 0


def wedge_child(workdir):
    """One collective with a wedged peer: the live ranks must get
    TransientDeviceError within FLAGS_collective_timeout_s, not a hang."""
    from paddle_tpu.distributed import env as denv
    from paddle_tpu.distributed.gang import default_gang
    from paddle_tpu.framework.errors import TransientDeviceError

    rank = denv.process_index()
    gang = default_gang("podsmoke-wedge")
    t0 = time.monotonic()
    try:
        gang.barrier()
    except TransientDeviceError:
        elapsed = time.monotonic() - t0
        with open(os.path.join(workdir, f"wedge.p{rank}.json"), "w") as f:
            json.dump({"elapsed": elapsed}, f)
        return WEDGE_RC
    return 40  # the wedged rank (or a watchdog failure): no raise


def serve_child(rank, rpc_dir, hb_dir):
    """Engine host: export a tiny model, serve it over the shared-dir RPC
    lane, and beat ``beat.p<rank>`` until the parent kills us."""
    import paddle_tpu as pt
    from paddle_tpu.distributed.heartbeat import FileHeartbeat, gang_beat_path
    from paddle_tpu.serving import Bucket, EngineServer, InferenceEngine

    pt.seed(1234)
    net = pt.nn.Sequential(pt.nn.Linear(8, 4))
    with tempfile.TemporaryDirectory() as tmp:
        prefix = os.path.join(tmp, "m")
        pt.inference.save_inference_model(
            prefix, net, [pt.static.InputSpec([None, None, 8], "float32")])
        eng = InferenceEngine(prefix, [Bucket(((4, 8),))],
                              max_batch_size=4, max_queue_delay_ms=1.0)
        eng.warmup()
        srv = EngineServer(eng, rpc_dir, name=f"engine.p{rank}")
        srv.start()
        hb = FileHeartbeat(gang_beat_path(hb_dir, rank))
        while True:  # parent SIGKILLs us; beats prove liveness until then
            hb.beat()
            time.sleep(0.1)


# -- parent-side helpers ----------------------------------------------------

def _child_env(workdir, world, rank, **extra):
    env = dict(os.environ)
    for var in ("COORDINATOR_ADDRESS", "PADDLE_TRAINER_ENDPOINTS",
                "PADDLE_TPU_GANG_TRANSPORT", "PADDLE_TPU_METRICS_JSONL",
                "POD_SMOKE_SLEEP_S", "FLAGS_fault_plan",
                "FLAGS_collective_timeout_s"):
        env.pop(var, None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TPU_GANG_DIR": os.path.join(workdir, "gang"),
    })
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _launch_pod(workdir, world, mode, launch_flags=(), env_extra=None,
                log_tag="pod"):
    """Start one launch process per rank; returns the Popen list."""
    os.makedirs(os.path.join(workdir, "gang"), exist_ok=True)
    procs = []
    for r in range(world):
        env = _child_env(workdir, world, r, **dict(env_extra or {}))
        log = open(os.path.join(workdir, f"{log_tag}.p{r}.log"), "wb")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             *launch_flags, SELF, mode, workdir],
            env=env, stdout=log, stderr=subprocess.STDOUT))
    return procs


def _wait_all(procs, deadline_s):
    t1 = time.time() + deadline_s
    rcs = []
    for p in procs:
        try:
            rcs.append(p.wait(timeout=max(1.0, t1 - time.time())))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            rcs.append(-999)
    return rcs


def _committed(ckpt_dir):
    from paddle_tpu.incubate.checkpoint import _META, _PREFIX

    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(n for n in os.listdir(ckpt_dir)
                  if n.startswith(_PREFIX)
                  and os.path.exists(os.path.join(ckpt_dir, n, _META)))


def _params(path):
    import numpy as np

    return dict(np.load(path))


def _identical(a, b):
    import numpy as np

    return set(a) == set(b) and all(np.array_equal(a[k], b[k]) for k in a)


# -- gates ------------------------------------------------------------------

def gate_sharded_bit_identity(tmp):
    """2-process sharded run == single-process run, bit for bit."""
    wd2 = os.path.join(tmp, "bitid-w2")
    os.makedirs(wd2)
    rcs = _wait_all(_launch_pod(wd2, 2, "--pod-trainer"), 180)
    if rcs != [0, 0]:
        return {"pass": False, "error": f"world=2 rcs={rcs}"}, None
    wd1 = os.path.join(tmp, "bitid-w1")
    os.makedirs(wd1)
    rcs = _wait_all(_launch_pod(wd1, 1, "--pod-trainer"), 180)
    if rcs != [0]:
        return {"pass": False, "error": f"world=1 rcs={rcs}"}, None
    p0 = _params(os.path.join(wd2, "out.p0.npz"))
    p1 = _params(os.path.join(wd2, "out.p1.npz"))
    solo = _params(os.path.join(wd1, "out.p0.npz"))
    ranks_agree = _identical(p0, p1)
    matches_solo = _identical(p0, solo)
    return {"pass": bool(ranks_agree and matches_solo),
            "ranks_agree": bool(ranks_agree),
            "matches_single_process": bool(matches_solo)}, p0


def _restore_attempt(tmp, tag, sleep_s):
    """One SIGKILL-mid-run attempt; returns (killed, rcs, wd, metrics)."""
    wd = os.path.join(tmp, tag)
    os.makedirs(wd)
    metrics = os.path.join(wd, "metrics.jsonl")
    procs = _launch_pod(
        wd, 2, "--pod-trainer",
        launch_flags=["--max-restarts=4", "--peer-timeout=3"],
        env_extra={"PADDLE_TPU_METRICS_JSONL": metrics,
                   "POD_SMOKE_SLEEP_S": sleep_s},
        log_tag="restore")
    ck1 = os.path.join(wd, "ck.p1")
    deadline = time.time() + 120
    killed = False
    try:
        while time.time() < deadline:
            if len(_committed(ck1)) >= 2:
                with open(os.path.join(wd, "pid.p1")) as f:
                    os.kill(int(f.read()), signal.SIGKILL)
                killed = True
                break
            if any(p.poll() is not None for p in procs):
                break  # a watchdog died before the kill window
            time.sleep(0.02)
        rcs = _wait_all(procs, 180)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return killed, rcs, wd, metrics


def gate_gang_restore(tmp, ref):
    """SIGKILL one host mid-run: gang restores, training finishes
    bit-identical to the uninterrupted run, metrics JSONL merges."""
    # on a starved machine the run can finish before this process ever
    # observes the kill window — slow the trainer's inter-round sleep and
    # retry rather than failing on gate-side scheduling noise
    killed = False
    for sleep_s in ("0.4", "1.0", "2.0"):
        killed, rcs, wd, metrics = _restore_attempt(
            tmp, f"restore-{sleep_s}", sleep_s)
        if killed or rcs != [0, 0]:
            break
    if not killed:
        return {"pass": False, "error": f"no kill window (rcs={rcs})"}
    if rcs != [0, 0]:
        return {"pass": False, "error": f"watchdog rcs={rcs}"}
    from paddle_tpu.observability.exporters import merge_jsonl

    merged = merge_jsonl(metrics, os.path.join(wd, "merged.jsonl"))
    per_rank = {}
    for rec in merged:
        r = rec.get("process_index")
        per_rank[r] = per_rank.get(r, 0) + 1
    restores = sum(rec.get("gang_restores", 0) for rec in merged
                   if rec.get("kind") == "gang_watch")
    identical = (_identical(_params(os.path.join(wd, "out.p0.npz")), ref)
                 and _identical(_params(os.path.join(wd, "out.p1.npz")), ref))
    ok = (identical and restores >= 1
          and per_rank.get(0, 0) >= 1 and per_rank.get(1, 0) >= 1)
    return {"pass": bool(ok),
            "final_params_bit_identical": bool(identical),
            "gang_restores": restores,
            "merged_records_per_rank": {str(k): v
                                        for k, v in per_rank.items()}}


def gate_wedged_gang(tmp):
    """3 ranks; rank 2 wedged at gang.collective by a latency fault: both
    live ranks raise TransientDeviceError within the armed deadline."""
    wd = os.path.join(tmp, "wedge")
    os.makedirs(wd)
    os.makedirs(os.path.join(wd, "gang"), exist_ok=True)
    procs = []
    for r in range(3):
        extra = {"FLAGS_collective_timeout_s": "2"}
        if r == 2:
            extra["FLAGS_fault_plan"] = \
                "site=gang.collective,nth=1,latency_ms=120000"
        env = _child_env(wd, 3, r, **extra)
        log = open(os.path.join(wd, f"wedge.p{r}.log"), "wb")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             SELF, "--wedge-child", wd],
            env=env, stdout=log, stderr=subprocess.STDOUT))
    rcs = _wait_all(procs[:2], 90)  # the live ranks
    procs[2].kill()
    procs[2].wait()
    elapsed = []
    for r in range(2):
        try:
            with open(os.path.join(wd, f"wedge.p{r}.json")) as f:
                elapsed.append(json.load(f)["elapsed"])
        except OSError:
            elapsed.append(None)
    within = all(e is not None and e < 10.0 for e in elapsed)
    ok = rcs == [WEDGE_RC, WEDGE_RC] and within
    return {"pass": bool(ok), "live_rank_rcs": rcs,
            "raised_within_deadline": bool(within),
            "seconds": [round(e, 2) if e is not None else None
                        for e in elapsed]}


def gate_router_failover(tmp):
    """Router fronting engines in two other processes; SIGKILL one host
    mid-traffic: zero lost accepted requests, peer_evictions >= 1."""
    import numpy as np

    from paddle_tpu.distributed.heartbeat import PeerHeartbeatMonitor
    from paddle_tpu.serving import RemoteEngineProxy, Router

    wd = os.path.join(tmp, "router")
    rpc, hb = os.path.join(wd, "rpc"), os.path.join(wd, "hb")
    for d in (rpc, hb):
        os.makedirs(d)
    kids = []
    for r in (1, 2):
        log = open(os.path.join(wd, f"serve.p{r}.log"), "wb")
        kids.append(subprocess.Popen(
            [sys.executable, SELF, "--serve-child", str(r), rpc, hb],
            env=_child_env(wd, 1, 0), stdout=log, stderr=subprocess.STDOUT))
    mon = router = None
    lost = completed = evictions = 0
    try:
        proxies = [RemoteEngineProxy(rpc, f"engine.p{r}", timeout_s=2.0)
                   for r in (1, 2)]
        for pr in proxies:
            pr.synthetic_inputs()  # blocks until the hello file lands
        mon = PeerHeartbeatMonitor(hb, world=3, self_rank=0,
                                   timeout=1.5, interval=0.1).start()
        router = Router(proxies, probe_interval_s=0.3, probe_timeout_s=5.0,
                        close_engines=False)
        router.bind_peer_liveness(mon, {0: 1, 1: 2})
        x = np.zeros((3, 8), np.float32)
        for _ in range(10):  # warm traffic over both hosts
            router.infer([x], timeout=30)
            completed += 1
        kids[1].send_signal(signal.SIGKILL)  # kill engine host rank 2
        kids[1].wait()
        t_end = time.monotonic() + 12
        while time.monotonic() < t_end:
            try:
                router.infer([x], timeout=30)
                completed += 1
            except Exception:  # noqa: BLE001 — a lost accepted request
                lost += 1
            evictions = router.metrics.snapshot().get("peer_evictions", 0)
            if evictions >= 1 and completed >= 30:
                break
        ok = lost == 0 and evictions >= 1 and completed >= 20
        return {"pass": bool(ok), "lost_accepted_requests": lost,
                "completed": completed, "peer_evictions": int(evictions)}
    finally:
        for kid in kids:
            if kid.poll() is None:
                kid.kill()
                kid.wait()
        if router is not None:
            router.close()
        if mon is not None:
            mon.stop()
        for pr in proxies:
            pr.close()


def gate_f803(tmp):
    """Injected gang-restart loop → storm exit 77 + F803; healthy watched
    run → exit 0 and F803 silent."""
    from paddle_tpu.analysis import RetraceMonitor
    from paddle_tpu.distributed.parallel import (RESTART_STORM_EXIT_CODE,
                                                 watch)

    class _AlwaysLost:
        def lost_workers(self):
            return (1,)

        def rearm(self, grace=None):
            pass

    class _NeverLost:
        def lost_workers(self):
            return ()

    with RetraceMonitor() as monitor:
        rc_storm = watch([sys.executable, "-c", "import time; time.sleep(60)"],
                         _sleep=0.05, storm_window=30, storm_restarts=3,
                         peer_monitor=_AlwaysLost(),
                         gang_label="podsmoke.storm")
        rc_ok = watch([sys.executable, "-c", "pass"],
                      peer_monitor=_NeverLost(), gang_label="podsmoke.ok")
    f803 = [d for d in monitor.diagnostics() if d.rule == "F803"]
    fired_on_storm = any("podsmoke.storm" in (d.location.file or "")
                         for d in f803)
    silent_on_healthy = not any("podsmoke.ok" in (d.location.file or "")
                                for d in f803)
    ok = (rc_storm == RESTART_STORM_EXIT_CODE and rc_ok == 0
          and fired_on_storm and silent_on_healthy)
    return {"pass": bool(ok), "storm_rc": rc_storm, "healthy_rc": rc_ok,
            "f803_fired_on_storm": bool(fired_on_storm),
            "f803_silent_on_healthy": bool(silent_on_healthy)}


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--pod-trainer":
        return pod_trainer(sys.argv[2])
    if len(sys.argv) > 1 and sys.argv[1] == "--wedge-child":
        return wedge_child(sys.argv[2])
    if len(sys.argv) > 1 and sys.argv[1] == "--serve-child":
        return serve_child(int(sys.argv[2]), sys.argv[3], sys.argv[4])

    t0 = time.time()
    with tempfile.TemporaryDirectory() as tmp:
        bitid, ref = gate_sharded_bit_identity(tmp)
        if not bitid["pass"]:
            gates = {"sharded_bit_identity": bitid}
            print(json.dumps({"pass": False, **gates,
                              "seconds": round(time.time() - t0, 1)}))
            return 1
        restore = gate_gang_restore(tmp, ref)
        wedge = gate_wedged_gang(tmp)
        router = gate_router_failover(tmp)
        f803 = gate_f803(tmp)
    gates = {"sharded_bit_identity": bitid, "gang_restore": restore,
             "wedged_gang": wedge, "router_failover": router, "f803": f803}
    passed = all(g["pass"] for g in gates.values())
    print(json.dumps({"pass": bool(passed), **gates,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

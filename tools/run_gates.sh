#!/usr/bin/env bash
# One-command reproduction of every number the round docs report
# (VERDICT r4 missing #7 — the reference ships paddle_build.sh +
# tools/test_runner.py; this is the paddle_tpu equivalent).
#
# Stages (each timed, JSON summary at the end):
#   analyze python -m paddle_tpu.analysis (static analysis, CPU, seconds)
#   fast    pytest -m fast           (~3 min sanity lane)
#   suite   pytest tests/            (full suite)
#   audit   tools/api_parity_audit.py (implemented/shimmed/missing counts)
#   dryrun  __graft_entry__.dryrun_multichip(8) on a virtual CPU mesh
#   perf-smoke tools/perf_smoke.py   (fused run_steps vs per-step, CPU, seconds)
#   serving-smoke tools/serving_smoke.py (closed compile set + KV-decode identity)
#   kernel-smoke tools/kernel_smoke.py (autotuner search + warm-restart cache hit)
#   tune-smoke tools/tune_smoke.py  (plan + serving measured search, warm replay, K701)
#   scenario-smoke tools/scenario_smoke.py (autoscaling loop under traffic chaos + disagg)
#   moe-smoke tools/moe_smoke.py (expert-sharded decode: closed set + balanced routing)
#   chaos-smoke tools/chaos_smoke.py (SIGKILL-resume bit identity + circuit recovery)
#   obs-smoke tools/obs_smoke.py   (metrics scrape + JSONL sink + serving spans)
#   router-smoke tools/router_smoke.py (replica kill -> zero-loss failover + rolling swap)
#   gen-smoke tools/gen_smoke.py (continuous batching: HOL p99, zero recompiles, probes)
#   tenancy-smoke tools/tenancy_smoke.py (multi-LoRA tenants: mixed-vs-serial bit identity, hot-add zero recompiles, noisy-neighbor cap)
#   quant-smoke tools/quant_smoke.py (int8/fp8 serving: margin-accounted tokens, equal-HBM slots, quantized rolling swap)
#   slo-smoke tools/slo_smoke.py (request tracing end-to-end + SLO burn-rate alert)
#   elastic-smoke tools/elastic_smoke.py (NaN rollback + exact resume + collective watchdog)
#   pod-smoke tools/pod_smoke.py (N-process gang: sharded bit identity, SIGKILL -> gang restore, wedge watchdog, router failover, F803)
#   bench   python bench.py          (only when a real TPU answers)
#
# Usage:  tools/run_gates.sh [--skip analyze|fast|suite|audit|dryrun|perf-smoke|serving-smoke|kernel-smoke|tune-smoke|scenario-smoke|moe-smoke|chaos-smoke|obs-smoke|router-smoke|gen-smoke|tenancy-smoke|quant-smoke|slo-smoke|elastic-smoke|pod-smoke|bench]...
#         tools/run_gates.sh --only suite
# Exit code: 0 iff every stage that ran passed.
set -u
cd "$(dirname "$0")/.."

SKIP=""
ONLY=""
while [ $# -gt 0 ]; do
  case "$1" in
    --skip) SKIP="$SKIP $2"; shift 2 ;;
    --only) ONLY="$2"; shift 2 ;;
    *) echo "unknown arg $1" >&2; exit 2 ;;
  esac
done

SUMMARY="$(mktemp)"
echo "{" > "$SUMMARY"
FAILED=0
FIRST=1

want() {  # does stage $1 run?
  if [ -n "$ONLY" ]; then [ "$ONLY" = "$1" ]; return; fi
  case " $SKIP " in *" $1 "*) return 1 ;; esac
  return 0
}

record() {  # stage status seconds detail
  [ $FIRST -eq 0 ] && echo "," >> "$SUMMARY"
  FIRST=0
  # JSON-encode the detail (backslashes/quotes/control chars in log tails)
  local detail_json
  detail_json=$(printf '%s' "$4" | python -c \
    'import json,sys; print(json.dumps(sys.stdin.read()[:160]))')
  printf '  "%s": {"status": "%s", "seconds": %s, "detail": %s}' \
    "$1" "$2" "$3" "$detail_json" >> "$SUMMARY"
  [ "$2" = "pass" ] || [ "$2" = "skipped" ] || FAILED=1
}

run_stage() {  # name cmd...
  local name="$1"; shift
  if ! want "$name"; then
    echo "== $name: skipped"
    record "$name" skipped 0 ""
    return
  fi
  echo "== $name: $*"
  local t0 t1 log status detail
  log="$(mktemp "/tmp/gate_${name}_XXXX.log")"
  t0=$(date +%s)
  if "$@" >"$log" 2>&1; then status=pass; else status=FAIL; fi
  t1=$(date +%s)
  tail -5 "$log"
  detail=$(tail -1 "$log")
  record "$name" "$status" $((t1 - t0)) "$detail"
  if [ "$status" = "FAIL" ]; then
    echo "== $name: FAIL ($((t1 - t0))s) — full log kept at $log"
  else
    echo "== $name: $status ($((t1 - t0))s)"
    rm -f "$log"
  fi
}

# static analysis first: cheapest gate, no device work (JAX_PLATFORMS=cpu)
run_stage analyze env JAX_PLATFORMS=cpu python -m paddle_tpu.analysis --strict \
  paddle_tpu.models.bert paddle_tpu.models.gpt \
  paddle_tpu.vision.models.resnet paddle_tpu.vision.models.vgg \
  paddle_tpu.vision.models.lenet paddle_tpu.vision.models.mobilenetv1 \
  paddle_tpu.vision.models.mobilenetv2
# concurrency lint over the framework's OWN source: lock-order inversions,
# locks held across blocking calls, unguarded cross-thread writes, bare
# Condition.waits (C10xx).  Error severity (a lock-order cycle) fails the
# gate; the fixture zoo in tests/test_concurrency_analysis.py proves the
# rules FIRE, this sweep proves the tree is clean
run_stage analyze-concurrency env JAX_PLATFORMS=cpu \
  python -m paddle_tpu.analysis --concurrency paddle_tpu/

run_stage fast   python -m pytest tests/ -m fast -q
run_stage suite  python -m pytest tests/ -q
run_stage audit  python tools/api_parity_audit.py
run_stage dryrun python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
# fused multi-step path exercised on every gate run (CPU: dispatch-count
# and numerical-equivalence property, not a throughput claim)
run_stage perf-smoke env JAX_PLATFORMS=cpu python tools/perf_smoke.py
# serving: closed compile set + exact padded/unpadded answers + KV-decode
# token identity (CPU correctness gate, not a throughput claim)
run_stage serving-smoke env JAX_PLATFORMS=cpu python tools/serving_smoke.py
# kernel autotuner: forced measured search in interpret mode, then a second
# process that must resolve every key from the on-disk cache (zero searches)
run_stage kernel-smoke env JAX_PLATFORMS=cpu python tools/kernel_smoke.py
# measured search beyond kernels: sharding-plan candidates timed as real
# fused train steps + serving dials timed against the deterministic bench
# trace, winners persisted (schema v2); a second process replays both from
# disk with zero searches, K701 silent on hits and firing on an injected
# post-warm search
run_stage tune-smoke env JAX_PLATFORMS=cpu python tools/tune_smoke.py
# autoscaling loop: seeded traffic chaos (flash crowd / diurnal / heavy tail /
# poison) drives SloEngine -> ReplicaPool; fleet scales up AND down in bounds
# with zero lost requests, S605 silent, closed per-engine compile sets; then
# the prefill-heavy burst replayed colo vs prefill/decode-disaggregated:
# decode-class p99 strictly better, tokens bit-identical
run_stage scenario-smoke env JAX_PLATFORMS=cpu python tools/scenario_smoke.py
# expert-sharded decode: 4-expert top-2 GPT behind the continuous engine
# with per-step routing inside the jitted step -> closed compile set, zero
# post-warmup XLA compiles, tokens bit-identical to eager greedy under
# ample capacity, every expert live (no dead experts / overflow), S606
# silent; the 0-expert build must publish no moe keys at all
run_stage moe-smoke env JAX_PLATFORMS=cpu python tools/moe_smoke.py
# resilience: injected checkpoint-write fault + SIGKILL -> bit-identical
# resume; injected serving fault -> circuit opens, sheds, recovers —
# all under the runtime lock sanitizer (zero C1004/C1005 asserted)
run_stage chaos-smoke env JAX_PLATFORMS=cpu FLAGS_lock_sanitizer=1 \
  python tools/chaos_smoke.py
# observability: live Prometheus scrape with advancing step counters,
# JSONL snapshot sink, and serving spans in the chrome trace
run_stage obs-smoke env JAX_PLATFORMS=cpu python tools/obs_smoke.py
# serving control plane: 1-of-3 replicas hard-failed mid-traffic -> every
# accepted request completes via failover, half-open re-admission after the
# cooldown, rolling swap_weights under load (zero rejects, zero recompiles)
# — all under the runtime lock sanitizer (zero C1004/C1005 asserted)
run_stage router-smoke env JAX_PLATFORMS=cpu FLAGS_lock_sanitizer=1 \
  python tools/router_smoke.py
# continuous batching decode plane: 1 long + many short requests -> short
# p99 at least 2x better than the legacy run-to-completion path, zero lost
# requests, zero post-warmup XLA recompiles, router probes stay green;
# paged KV gate: same HBM budget holds strictly more resident slots with
# CoW shared-prefix reuse + speculative decoding, tokens bit-identical to
# dense greedy and tokens/s no worse, closed compile set (buckets + 3)
run_stage gen-smoke env JAX_PLATFORMS=cpu python tools/gen_smoke.py
# multi-tenant serving: mixed multi-LoRA traffic bit-identical to per-tenant
# serial baselines, adapter hot-add mid-traffic with zero post-warmup XLA
# compiles, noisy-neighbor flooder capped at its token budget with victim
# p99 within bound, S607 silent on the healthy run
run_stage tenancy-smoke env JAX_PLATFORMS=cpu python tools/tenancy_smoke.py
# quantized serving: int8/fp8 engines may flip near-tie tokens only (margin
# accounting vs fp32), an int8-KV pool holds strictly more resident slots
# at equal measured bytes with tokens/s no worse, and a quantized rolling
# swap across a router compiles nothing
run_stage quant-smoke env JAX_PLATFORMS=cpu python tools/quant_smoke.py
# request tracing + SLO: full router->slot span tree in the merged chrome
# export with zero post-warmup compiles, injected decode latency -> burn-rate
# alert + M903 + scale-up signal through the router hook, off means off
run_stage slo-smoke env JAX_PLATFORMS=cpu python tools/slo_smoke.py
# elastic training: injected NaN -> exactly one rollback + finite finish,
# SIGKILL mid-epoch -> bit-identical resume (shuffle order, RNG, params),
# wedged collective -> watchdog raises within the deadline, F802 on a
# rollback loop, disabled supervisor is a plain loop
run_stage elastic-smoke env JAX_PLATFORMS=cpu python tools/elastic_smoke.py
# pod-scale multi-host: N real processes through distributed.launch —
# sharded-data training bit-identical to single-process, SIGKILLed host ->
# gang restore from the agreed checkpoint with bit-identical finals,
# wedged collective -> TransientDeviceError on every live rank within the
# deadline, Router fronting cross-process engines loses zero accepted
# requests across a host kill, F803 on a restore storm (per-process
# metrics JSONL merged via exporters.merge_jsonl)
run_stage pod-smoke env JAX_PLATFORMS=cpu python tools/pod_smoke.py

# bench only when a real accelerator answers within 60s
if want bench; then
  if timeout 60 python -c "import jax; assert jax.devices()[0].platform not in ('cpu',)" \
      >/dev/null 2>&1; then
    run_stage bench python bench.py
  else
    echo "== bench: skipped (no TPU reachable)"
    record bench skipped 0 "no TPU reachable"
  fi
fi

echo "}" >> "$SUMMARY"
echo
echo "=== gate summary ==="
cat "$SUMMARY"
cp "$SUMMARY" GATES.json
echo
echo "written to GATES.json"
exit $FAILED

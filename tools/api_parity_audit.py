#!/usr/bin/env python
"""API-parity audit: compare paddle_tpu's public namespaces against the
reference tree's import surface.

Counterpart of the reference's API-freeze tooling (tools/check_api_compatible.py
+ paddle/fluid/API.spec): instead of freezing signatures, this walks the
reference package __init__ files, extracts every publicly imported name,
and reports a THREE-VALUED classification per namespace:

  implemented  resolves to a real implementation
  shimmed      resolves to an honest hint-shim that raises
               UnimplementedError naming the eager equivalent
               (``__shim__`` marker set by fluid.layers.__getattr__)
  missing      does not resolve at all

Run from the repo root:

    python tools/api_parity_audit.py [--ref /root/reference/python/paddle]

Exit status 1 when any audited namespace has MISSING names (shims are
reported but do not fail the audit — they are present-by-contract, not
implemented).  `fluid.layers`-style modules that resolve names lazily via
__getattr__ are probed with getattr, which those modules support by
design (shims resolve; only unknown names raise).
"""
from __future__ import annotations

import argparse
import importlib
import os
import re
import sys

#: (paddle_tpu module suffix, reference path fragment)
NAMESPACES = [
    ("", "."),
    ("nn", "nn"),
    ("nn.functional", "nn/functional"),
    ("tensor", "tensor"),
    ("optimizer", "optimizer"),
    ("io", "io"),
    ("metric", "metric"),
    ("static", "static"),
    ("static.nn", "static/nn"),
    ("jit", "jit"),
    ("amp", "amp"),
    ("vision", "vision"),
    ("vision.models", "vision/models"),
    ("vision.transforms", "vision/transforms"),
    ("vision.datasets", "vision/datasets"),
    ("text", "text"),
    ("utils", "utils"),
    ("distributed", "distributed"),
    ("incubate", "incubate"),
]

#: reference names that are intentionally absent (internal machinery the
#: TPU-native design replaces wholesale — each with the replacing design).
#: Empty since round 5: jit.dy2static is real now (paddle_tpu/dy2static.py,
#: the AST-lite transpiler).
WAIVED = {}


def ref_names(ref_root: str, rel: str) -> set:
    path = os.path.join(ref_root, rel)
    if os.path.isdir(path):
        path = os.path.join(path, "__init__.py")
    if not os.path.exists(path):
        return set()
    src = open(path).read()
    names = set()
    for m in re.finditer(r"^from\s+[\w.]+\s+import\s+(.+?)(?:#.*)?$",
                         src, re.M):
        for n in m.group(1).split(","):
            n = n.strip().split(" as ")[-1].strip()
            if n.isidentifier() and not n.startswith("_") \
                    and n != "print_function":
                names.add(n)
    return names


def fluid_layers_names(ref_root: str) -> set:
    """fluid.layers aggregates submodule __all__ lists."""
    base = os.path.join(ref_root, "fluid/layers")
    names = set()
    for fname in ("nn.py", "tensor.py", "control_flow.py", "loss.py",
                  "detection.py", "sequence_lod.py", "rnn.py",
                  "learning_rate_scheduler.py", "io.py", "metric_op.py"):
        p = os.path.join(base, fname)
        if not os.path.exists(p):
            continue
        m = re.search(r"__all__\s*=\s*\[(.*?)\]", open(p).read(), re.S)
        if m:
            names.update(re.findall(r"'(\w+)'", m.group(1)))
    return names


def classify(module, names, waive_prefix=""):
    """Split resolved names into (implemented, shimmed, missing)."""
    impl, shims, missing = [], [], []
    for n in sorted(names):
        if f"{waive_prefix}.{n}" in WAIVED:
            continue
        if not hasattr(module, n):
            missing.append(n)
            continue
        obj = getattr(module, n)
        (shims if getattr(obj, "__shim__", False) else impl).append(n)
    return impl, shims, missing


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference/python/paddle")
    args = ap.parse_args()

    sys.path.insert(0, os.getcwd())
    total_missing = 0
    total_shimmed = 0
    total_impl = 0
    rows = []
    for mod, rel in NAMESPACES:
        names = ref_names(args.ref, rel)
        if not names:
            continue
        target = "paddle_tpu" + (f".{mod}" if mod else "")
        try:
            ours = importlib.import_module(target)
        except Exception as e:  # noqa: BLE001
            rows.append((mod or "paddle", len(names), 0, [],
                         [f"IMPORT: {e}"]))
            total_missing += len(names)
            continue
        impl, shims, missing = classify(ours, names,
                                        waive_prefix=mod)
        total_missing += len(missing)
        total_shimmed += len(shims)
        total_impl += len(impl)
        rows.append((mod or "paddle", len(names), len(impl), shims, missing))

    # fluid.layers: aggregated __all__, resolved via __getattr__ shims
    lnames = fluid_layers_names(args.ref)
    if lnames:
        fl = importlib.import_module("paddle_tpu.fluid.layers")
        impl, shims, missing = classify(fl, lnames, waive_prefix="fluid.layers")
        total_missing += len(missing)
        total_shimmed += len(shims)
        total_impl += len(impl)
        rows.append(("fluid.layers", len(lnames), len(impl), shims, missing))

    width = max(len(r[0]) for r in rows) + 2
    print(f"{'namespace':<{width}} {'ref':>5} {'impl':>5} {'shim':>5} "
          f"{'miss':>5}")
    shim_rows = []
    for mod, n_ref, n_impl, shims, missing in rows:
        print(f"{mod:<{width}} {n_ref:>5} {n_impl:>5} {len(shims):>5} "
              f"{len(missing):>5}")
        shim_rows.extend((mod, n) for n in shims)
        if missing:
            for name in missing[:20]:
                print(f"    - MISSING: {name}")
    if shim_rows:
        # every remaining shim prints its one-line justification (the
        # eager equivalent its error names) so the count is defensible
        print("\nremaining shims (each raises naming its replacement):")
        for mod, name in shim_rows:
            m = importlib.import_module(
                "paddle_tpu" + (f".{mod}" if mod != "paddle" else ""))
            doc = (getattr(getattr(m, name), "__doc__", "") or "")
            just = doc.split("eager equivalent:")[-1].strip() or doc.strip()
            print(f"  ~ {mod}.{name}: {just.splitlines()[0] if just else '?'}")
    print(f"\nimplemented: {total_impl}  shimmed: {total_shimmed}  "
          f"missing: {total_missing}")
    return 1 if total_missing else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""perf-smoke gate: the fused multi-step path on a tiny MLP, CPU, seconds.

Two lanes over identically-initialized programs and identical batches:

  per_step   N Executor.run calls   -> N device dispatches
  fused      Executor.run_steps(N)  -> ONE device dispatch (lax.scan chain)

Asserts (1) the fused chain issues exactly one dispatch where the per-step
lane issues N — the dispatch-amortization property the bench configs rely
on — and (2) the two lanes produce numerically matching per-step losses
and final parameters, so the fused path is exercised end-to-end on every
gate run.  Emits one JSON line with both wall-clock timings (CPU timings
are NOT a throughput claim; the property under test is dispatch count and
equivalence).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_STEPS = 4


def build():
    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu.static.graph import reset_default_programs

    paddle.seed(0)  # identical init across lanes
    reset_default_programs()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [-1, 16])
        y = fluid.data("y", [-1, 1])
        h = fluid.layers.fc(x, 32, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    return exe, main, loss


def main():
    rng = np.random.RandomState(0)
    X = rng.rand(N_STEPS, 8, 16).astype(np.float32)
    Y = rng.rand(N_STEPS, 8, 1).astype(np.float32)

    exe_a, main_a, loss_a = build()
    t0 = time.perf_counter()
    per_step = [float(exe_a.run(main_a, feed={"x": X[t], "y": Y[t]},
                                fetch_list=[loss_a])[0])
                for t in range(N_STEPS)]
    dt_per_step = time.perf_counter() - t0
    params_a = {k: np.asarray(v) for k, v in main_a.parameters_numpy().items()}

    exe_b, main_b, loss_b = build()
    t0 = time.perf_counter()
    fused, = exe_b.run_steps(main_b, feed={"x": X, "y": Y},
                             fetch_list=[loss_b], iterations=N_STEPS)
    dt_fused = time.perf_counter() - t0
    params_b = {k: np.asarray(v) for k, v in main_b.parameters_numpy().items()}

    assert exe_a.dispatches == N_STEPS, exe_a.cache_stats()
    assert exe_b.dispatches == 1, exe_b.cache_stats()
    np.testing.assert_allclose(np.asarray(fused).ravel(), per_step,
                               rtol=1e-5, atol=1e-6)
    # param names differ only in the program-idx prefix (_<idx>_fc_...)
    key = lambda n: n.split("_", 2)[2]  # noqa: E731
    remap = {key(k): v for k, v in params_a.items()}
    for k, v in params_b.items():
        np.testing.assert_allclose(v, remap[key(k)], rtol=1e-5, atol=1e-6)

    print(json.dumps({
        "metric": "perf_smoke_fused_chain",
        "n_steps": N_STEPS,
        "per_step_dispatches": exe_a.dispatches,
        "fused_dispatches": exe_b.dispatches,
        "per_step_wall_s": round(dt_per_step, 4),
        "fused_wall_s": round(dt_fused, 4),
        "losses_match": True, "params_match": True,
    }), flush=True)
    print(f"perf-smoke OK: {N_STEPS} steps -> {exe_b.dispatches} dispatch "
          f"(per-step lane: {exe_a.dispatches})")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Router gate: replica kill mid-traffic, recovery, rolling swap (CPU).

One-command proof of the serving control plane's three contracts, cheap
enough for every gate run:

1. **Failover** — hard-fail 1 of 3 replicas while traffic is flowing;
   every ACCEPTED request must still complete with the right answer
   (zero lost), the dead replica's circuit must trip it out of rotation.
2. **Recovery** — after the cooldown, a half-open synthetic probe must
   re-admit the (now healthy) replica.
3. **Rolling weight swap** — ``swap_weights_rolling`` under live traffic
   must reject zero requests, serve the NEW weights on every replica
   afterwards, and compile nothing (the compile set stays closed).

The whole episode runs under the runtime lock-order sanitizer
(``FLAGS_lock_sanitizer=1``): a fourth gate asserts zero C1004 cycles
and zero C1005 long holds across the router/batcher/replica lock set.

Prints one JSON line; exit 0 iff all four gates hold.
"""
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("FLAGS_lock_sanitizer", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import nn  # noqa: E402
from paddle_tpu.framework.errors import TransientDeviceError  # noqa: E402
from paddle_tpu.serving import Bucket, InferenceEngine, Router  # noqa: E402


class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 4)

    def forward(self, x):
        return self.fc(x)


def _export(tmp, name, seed):
    pt.seed(seed)
    net = _Net()
    prefix = os.path.join(tmp, name)
    pt.inference.save_inference_model(
        prefix, net, [pt.static.InputSpec([None, None, 8], "float32")])
    return prefix, net


class _Traffic:
    """Background request stream; records every accepted request's fate."""

    def __init__(self, router, x):
        self.router = router
        self.x = x
        self.results = []
        self.failures = []
        self.rejected = 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.is_set():
            try:
                fut = self.router.submit([self.x])
            except Exception:  # noqa: BLE001 — admission refusal
                self.rejected += 1
                continue
            try:
                self.results.append(fut.result(60)[0])
            except Exception as e:  # noqa: BLE001 — an ACCEPTED loss
                self.failures.append(repr(e))

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join(60)


def main():
    t0 = time.time()
    COOLDOWN_MS = 1000.0
    with tempfile.TemporaryDirectory() as tmp:
        prefix1, net1 = _export(tmp, "v1", seed=7)
        prefix2, net2 = _export(tmp, "v2", seed=23)
        x = np.random.RandomState(0).randn(3, 8).astype("float32")
        want1 = np.asarray(net1(x[None]))[0]
        want2 = np.asarray(net2(x[None]))[0]

        engines = [InferenceEngine(prefix1, [Bucket(((4, 8),))],
                                   max_queue_delay_ms=0.0,
                                   retry_transient=False,
                                   circuit_breaker=False,
                                   name=f"smoke-eng{i}")
                   for i in range(3)]
        router = Router(engines, name="smoke-router",
                        probe_interval_s=None,  # probes driven explicitly
                        circuit_kw={"failure_threshold": 1.0, "window": 2,
                                    "cooldown_ms": COOLDOWN_MS,
                                    "half_open_probes": 1})
        compiles_warm = router.warmup()

        # -- gate 1: hard-fail replica 0 mid-traffic --------------------------
        real_runner = engines[0]._batcher._runner

        def dead_runner(bucket, reqs):
            raise TransientDeviceError("smoke: replica 0 hard-failed")

        with _Traffic(router, x) as traffic:
            time.sleep(0.2)                        # healthy baseline
            engines[0]._batcher._runner = dead_runner
            while router.replica(0).state == "healthy":  # trip under load
                time.sleep(0.01)
            time.sleep(0.2)                        # keep serving degraded
        exact = all(np.allclose(r, want1, atol=1e-5) for r in traffic.results)
        s = router.stats()
        g1 = {"accepted_failed": len(traffic.failures),
              "rejected": traffic.rejected,
              "completed": len(traffic.results),
              "exact": bool(exact),
              "failovers": s["failovers"],
              "replica0_state": router.replica(0).state}
        gate1 = (not traffic.failures and traffic.rejected == 0 and exact
                 and s["failovers"] >= 1
                 and g1["replica0_state"] == "unhealthy")

        # -- gate 2: recovery after cooldown via half-open probe --------------
        engines[0]._batcher._runner = real_runner   # the replica heals
        router.probe_now()                          # cooldown NOT elapsed
        still_out = router.replica(0).state == "unhealthy"
        time.sleep(COOLDOWN_MS / 1e3 + 0.2)
        router.probe_now()                          # half-open probe passes
        g2 = {"held_through_cooldown": bool(still_out),
              "replica0_state": router.replica(0).state,
              "healthy": router.healthy_count(),
              "readmissions": router.stats()["readmissions"]}
        gate2 = (still_out and g2["replica0_state"] == "healthy"
                 and g2["healthy"] == 3)

        # -- gate 3: rolling weight swap under traffic, zero recompiles -------
        with _Traffic(router, x) as traffic2:
            time.sleep(0.1)
            swapped = router.swap_weights_rolling(prefix2 + ".pdiparams",
                                                  drain_timeout=30)
            time.sleep(0.1)
        fresh = [np.allclose(router.infer([x], timeout=60)[0], want2,
                             atol=1e-5) for _ in range(6)]
        compiles_after = sum(e.compile_count for e in engines)
        g3 = {"swapped": swapped,
              "accepted_failed": len(traffic2.failures),
              "rejected": traffic2.rejected,
              "completed": len(traffic2.results),
              "fresh_weights": bool(all(fresh)),
              "compiles_warm": compiles_warm,
              "compiles_after": compiles_after}
        gate3 = (swapped == 3 and not traffic2.failures
                 and traffic2.rejected == 0 and all(fresh)
                 and compiles_after == compiles_warm)
        router.close()

    # -- gate 4: lock sanitizer saw the whole episode, zero violations ----
    from paddle_tpu.framework import locking
    lk = locking.stats()
    g4 = {"enabled": lk["enabled"], "acquires": lk["acquires"],
          "edges": lk["edges"], "cycles": lk["cycles"],
          "long_holds": lk["long_holds"],
          "violations": locking.violations()[:4]}
    gate4 = (lk["enabled"] and lk["acquires"] > 0
             and lk["cycles"] == 0 and lk["long_holds"] == 0)

    passed = gate1 and gate2 and gate3 and gate4
    print(json.dumps({"pass": bool(passed),
                      "failover": g1, "recovery": g2, "rolling_swap": g3,
                      "lock_sanitizer": g4,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

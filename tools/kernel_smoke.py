"""Kernel-autotuner gate: measured search + persistent cache, on CPU.

One-command proof of the ``ops.autotune`` contracts, cheap enough for
every gate run (forced measurement in Pallas interpret mode, tiny
shapes):

1. **Cold process** — with a fresh cache file and
   ``FLAGS_kernel_autotune=force``, every kernel (flash fwd + both
   backwards via grad, conv1x1+BN, layernorm_residual, softmax_xent)
   resolves its tiles through a timed search: ``searches > 0``,
   ``configs_timed > 0``, an ``("autotune", ...)`` trace event fires per
   kernel, and the cache file lands on disk with one entry per key.
2. **Warm process** — a second, separate process over the same cache
   file does ZERO timed searches: every key resolves as ``disk_hits``
   (then memory hits), so a production restart never re-measures.

The parent spawns each phase as its own subprocess so the warm run
proves *process-level* persistence (nothing survives but the file).
Prints one JSON line; exit 0 iff both phases hold.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

_CHILD = """
import json, sys

import numpy as np

from paddle_tpu.framework import trace_events
from paddle_tpu.ops import autotune
from paddle_tpu.ops.flash_attention import flash_attention
from paddle_tpu.ops.fused_conv1x1_bn import conv1x1_bn_stats
from paddle_tpu.ops.fused_layernorm import layernorm_residual
from paddle_tpu.ops.fused_softmax_xent import softmax_cross_entropy

import jax
import jax.numpy as jnp

events = []
trace_events.register(lambda site, info: events.append(
    {"site": list(site), "event": info.get("event")}))

rng = np.random.RandomState(0)

# flash: fwd + grad (grad drives the two backward kernels' tuners)
q = jnp.asarray(rng.randn(1, 2, 64, 32), jnp.float32)
k = jnp.asarray(rng.randn(1, 2, 64, 32), jnp.float32)
v = jnp.asarray(rng.randn(1, 2, 64, 32), jnp.float32)
loss = lambda q, k, v: flash_attention(q, k, v, causal=True).sum()
out = flash_attention(q, k, v, causal=True)
gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
assert np.isfinite(np.asarray(out)).all() and np.isfinite(np.asarray(gq)).all()

# conv1x1 + bn stats
x = jnp.asarray(rng.randn(64, 16), jnp.float32)
w = jnp.asarray(rng.randn(16, 32), jnp.float32)
y, s, sq = conv1x1_bn_stats(x, w)
np.testing.assert_allclose(np.asarray(y), np.asarray(x) @ np.asarray(w),
                           rtol=1e-5, atol=1e-5)

# layernorm + residual
a = jnp.asarray(rng.randn(48, 32), jnp.float32)
r = jnp.asarray(rng.randn(48, 32), jnp.float32)
g = jnp.ones((32,), jnp.float32)
b = jnp.zeros((32,), jnp.float32)
sres, yn = layernorm_residual(a, r, g, b)
np.testing.assert_allclose(np.asarray(sres), np.asarray(a + r),
                           rtol=1e-6, atol=1e-6)

# softmax cross-entropy
logits = jnp.asarray(rng.randn(32, 96), jnp.float32)
labels = jnp.asarray(rng.randint(0, 96, 32), jnp.int32)
lo = softmax_cross_entropy(logits, labels)
ref = -np.take_along_axis(
    np.asarray(jax.nn.log_softmax(logits, -1)),
    np.asarray(labels)[:, None], 1)[:, 0]
np.testing.assert_allclose(np.asarray(lo), ref, rtol=1e-5, atol=1e-5)

print(json.dumps({"counters": autotune.get_counters(),
                  "events": events,
                  "cache_path": autotune.cache_path()}))
"""


def _run_child(cache_file):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               FLAGS_kernel_autotune="force",
               FLAGS_kernel_tuning_cache=cache_file)
    proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout + proc.stderr)
        raise SystemExit(f"kernel_smoke child failed (rc={proc.returncode})")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main():
    t0 = time.time()
    fd, cache_file = tempfile.mkstemp(suffix=".json", prefix="ktune_")
    os.close(fd)
    os.unlink(cache_file)  # children create it; start truly cold
    try:
        cold = _run_child(cache_file)
        warm = _run_child(cache_file)
    finally:
        if os.path.exists(cache_file):
            entries = len(json.load(open(cache_file)).get("entries", {}))
            os.unlink(cache_file)
        else:
            entries = 0

    def total(per_kernel):  # get_counters() is {kernel: {counter: n}}
        out = {}
        for d in per_kernel.values():
            for key, n in d.items():
                out[key] = out.get(key, 0) + n
        return out

    cc, wc = total(cold["counters"]), total(warm["counters"])
    cold_kernels = sorted({e["site"][1] for e in cold["events"]
                           if e["site"][0] == "autotune"})
    checks = {
        # cold process: every kernel measured, events observed, file written
        "cold_searches": cc["searches"] >= 5,
        "cold_timed": cc["configs_timed"] > 0,
        "cold_events": len(cold_kernels) >= 5,
        "cache_entries": entries >= 5,
        # warm process: pure disk hits — ZERO timed searches after restart
        "warm_zero_searches": wc["searches"] == 0,
        "warm_zero_timed": wc["configs_timed"] == 0,
        "warm_disk_hits": wc["disk_hits"] >= 5,
    }
    ok = all(checks.values())
    print(json.dumps({
        "gate": "kernel_smoke", "ok": ok, "checks": checks,
        "cold_counters": cc, "warm_counters": wc,
        "kernels_tuned": cold_kernels, "cache_entries": entries,
        "seconds": round(time.time() - t0, 1)}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Multi-tenant serving gate: isolation, identity, closed set (CPU).

One-command proof of the tenancy subsystem's contracts, cheap enough
for every gate run:

1. **Mixed-vs-serial bit identity** — two LoRA tenants plus a base
   tenant interleaved on ONE paged engine under a
   :class:`TenantScheduler` must produce tokens bit-identical to
   per-tenant serial baselines on a fresh engine with explicit adapter
   ids: the batched adapter gather and the weighted-fair interleaving
   are invisible to every tenant's output.
2. **Adapter hot-add on a warm engine** — the second adapter installs
   MID-TRAFFIC and serves immediately, with ZERO post-warmup XLA
   compile events (table edits are argument edits, never recompiles).
3. **Noisy neighbor** — the seeded ``noisy_neighbor`` scenario with a
   hard (no-refill) token budget on the flooder: the flooder is capped
   near its budget while the victims' p99 stays within a bound of the
   flood-free run of the SAME victim schedule; zero victims lost.
4. **S607 silent on a healthy run** — the analysis monitor watching the
   mixed run must report no multi-tenant isolation findings.

Prints one JSON line; exit 0 iff all four gates hold.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.monitoring  # noqa: E402
import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.analysis import RetraceMonitor  # noqa: E402
from paddle_tpu.lora import random_adapter  # noqa: E402
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM  # noqa: E402
from paddle_tpu.serving import (GenerationEngine, TenantScheduler,  # noqa: E402
                                TenantSpec, noisy_neighbor, run_scenario)

# ground truth for "zero post-warmup recompiles": count actual XLA
# backend compile requests (fires even when the jaxpr cache hits)
_XLA_COMPILES = [0]
jax.monitoring.register_event_listener(
    lambda name, **kw: _XLA_COMPILES.__setitem__(0, _XLA_COMPILES[0] + 1)
    if name == "/jax/compilation_cache/compile_requests_use_cache" else None)

FLOOD_BUDGET = 30  # hard one-shot token cap for the flooder tenant
NOISY_SLOTS = 4


def _lora_model():
    pt.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_position=64, dropout=0.0,
                    lora_capacity=2, lora_rank=4)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _plain_model():
    pt.seed(13)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_position=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def gate_mixed_and_hot_add(model):
    """Gates 1 + 2 + 4: serial baselines, then the mixed tenancy run
    with a mid-traffic adapter install, under the analysis monitor."""
    a0 = random_adapter(model, "acme-a", rank=4, seed=20, alpha=32.0,
                        std=0.2)
    a1 = random_adapter(model, "globex-a", rank=4, seed=21, alpha=32.0,
                        std=0.2)
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 97, size=4 + (k % 5)).astype(np.int32)
               for k in range(4)]
    budgets = [6, 8, 5, 7]

    refs = {}
    with GenerationEngine(model, prompt_buckets=[16], batch_size=2,
                          cache_len=48, paged=True, kv_page_size=8,
                          name="ten-smoke-serial") as ser:
        ser.install_adapter(0, a0)
        ser.install_adapter(1, a1)
        ser.warmup()
        for tn, aid in (("acme", 0), ("globex", 1), ("base", -1)):
            refs[tn] = [ser.generate(p, b, timeout=120,
                                     adapter_id=aid).tolist()
                        for p, b in zip(prompts, budgets)]

    ten = TenantScheduler([TenantSpec("acme", weight=2.0, adapter_id=0),
                           TenantSpec("globex", adapter_id=1),
                           TenantSpec("base", adapter_id=-1)])
    with RetraceMonitor(budget=8) as mon:
        with GenerationEngine(model, prompt_buckets=[16], batch_size=2,
                              cache_len=48, paged=True, kv_page_size=8,
                              tenancy=ten, name="ten-smoke-mixed") as eng:
            eng.install_adapter(0, a0)  # adapter 1 hot-adds mid-traffic
            warm = eng.warmup()
            xla0 = _XLA_COMPILES[0]
            outs = {}
            # phase 1: acme + base interleaved
            futs = [(tn, i, eng.submit(p, b, tenant=tn))
                    for i, (p, b) in enumerate(zip(prompts, budgets))
                    for tn in ("acme", "base")]
            # phase 2: hot-add adapter 1 while phase-1 decode is live,
            # then serve globex through it immediately
            eng.install_adapter(1, a1)
            futs += [("globex", i, eng.submit(p, b, tenant="globex"))
                     for i, (p, b) in enumerate(zip(prompts, budgets))]
            mismatches = 0
            for tn, i, f in futs:
                out = f.result(120).tolist()
                outs.setdefault(tn, {})[i] = out
                if out != refs[tn][i]:
                    mismatches += 1
            xla_recompiles = _XLA_COMPILES[0] - xla0
            st = eng.stats()
            time.sleep(0.15)  # one publish tick carries the bus snapshot
        s607 = [d for d in mon.diagnostics() if d.rule == "S607"]
    return {
        "bit_identical_mixed_vs_serial": mismatches == 0,
        "mismatches": mismatches,
        "warmup_compiles": warm,
        "hot_add_xla_recompiles": xla_recompiles,
        "hot_add_closed": (xla_recompiles == 0
                           and st["compile_count"] == warm),
        "adapter_installs": int(st.get("adapter_installs", 0)),
        "completed": int(st.get("completed", 0)),
        "s607_findings": len(s607),
        "s607_silent": not s607,
    }


def gate_noisy_neighbor(model):
    """Gate 3: the flooder's hard budget caps its delivered tokens while
    the victims' p99 stays within a bound of the flood-free run."""
    kw = dict(duration_s=4.0, tenants=("acme", "globex"),
              flooder="initech", rps=3.0, flood_at=0.2, seed=5)
    flooded = noisy_neighbor(flood_rps=15.0, **kw)
    calm = noisy_neighbor(flood_rps=0.001, **kw)  # no flood arrivals

    def run(scenario):
        ten = TenantScheduler([
            TenantSpec("acme"), TenantSpec("globex"),
            TenantSpec("initech", token_budget=FLOOD_BUDGET)])
        with GenerationEngine(model, prompt_buckets=[16],
                              batch_size=NOISY_SLOTS, cache_len=32,
                              paged=True, kv_page_size=8, tenancy=ten,
                              name="ten-smoke-noisy") as eng:
            eng.warmup()
            rep = run_scenario(eng, scenario, deadline_ms=8000.0,
                               result_timeout_s=120.0)
            stats = eng.stats()
        return rep, stats

    rep_f, st_f = run(flooded)
    rep_c, _ = run(calm)

    def victim_p99(rep):
        lat = sorted(r["latency_ms"] for r in rep["records"]
                     if r["tenant"] in ("acme", "globex") and r.get("ok"))
        return lat[min(int(round(0.99 * len(lat))), len(lat) - 1)] \
            if lat else -1.0

    def victims_done(rep):
        recs = [r for r in rep["records"]
                if r["tenant"] in ("acme", "globex")]
        return (len(recs),
                sum(1 for r in recs if r.get("ok")))

    flood_tokens = sum(len(r["tokens"]) for r in rep_f["records"]
                       if r["tenant"] == "initech" and r.get("ok"))
    n_victims, ok_victims = victims_done(rep_f)
    p99_f, p99_c = victim_p99(rep_f), victim_p99(rep_c)
    # the flooder can overshoot by at most the in-flight slots' budgets
    # (charges land at harvest; the next step preempts)
    cap = FLOOD_BUDGET + NOISY_SLOTS * 8
    # generous CPU-timing bound: flooded victim p99 within 4x + 250ms of
    # the flood-free p99 of the SAME victim arrival schedule
    bound_ms = 4.0 * max(p99_c, 1.0) + 250.0
    return {
        "flood_requests": sum(1 for r in rep_f["records"]
                              if r["tenant"] == "initech"),
        "flooder_tokens": flood_tokens,
        "flooder_budget": FLOOD_BUDGET,
        "flooder_capped": bool(flood_tokens <= cap),
        "victims": n_victims,
        "victims_completed": ok_victims,
        "victims_all_served": bool(ok_victims == n_victims
                                   and rep_f["lost"] == 0),
        "victim_p99_ms_flooded": round(p99_f, 1),
        "victim_p99_ms_calm": round(p99_c, 1),
        "victim_p99_bound_ms": round(bound_ms, 1),
        "victim_p99_within_bound": bool(0 < p99_f <= bound_ms),
        "tenant_preempted": int(st_f.get("tenant_preempted", 0)),
        "throttled_steps": int(st_f.get("tenant_throttled_steps", 0)),
    }


def main():
    t0 = time.time()
    mixed = gate_mixed_and_hot_add(_lora_model())
    noisy = gate_noisy_neighbor(_plain_model())
    passed = (mixed["bit_identical_mixed_vs_serial"]
              and mixed["hot_add_closed"]
              and mixed["s607_silent"]
              and noisy["flooder_capped"]
              and noisy["victims_all_served"]
              and noisy["victim_p99_within_bound"])
    print(json.dumps({"pass": bool(passed), "mixed": mixed,
                      "noisy": noisy,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

"""paddle_tpu.amp — automatic mixed precision (paddle.amp parity).

bf16-first: on TPU the MXU computes natively in bfloat16, which shares
f32's exponent range — ``auto_cast`` alone is the whole story and
GradScaler is only needed for fp16-parity workloads.
"""
from .auto_cast import (  # noqa: F401
    auto_cast,
    amp_guard,
    decorate,
    WHITE_CLASSES,
    BLACK_CLASSES,
)
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401

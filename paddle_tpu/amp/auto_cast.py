"""Automatic mixed precision — autocast policy.

Parity: paddle.amp.auto_cast (reference: python/paddle/fluid/dygraph/amp/
auto_cast.py:90 amp_guard; per-op white/black lists
contrib/mixed_precision/fp16_lists.py; C++ cast insertion
imperative/amp_auto_cast.cc).

TPU-native: the mixed-precision dtype is **bfloat16** (same exponent range
as f32 — no loss scaling needed; fp16 is supported for parity and needs
GradScaler).  The reference inserts cast ops around each kernel by op name;
here the policy acts at the Layer boundary: inside ``auto_cast()``,
white-list layers (matmul/conv compute that the MXU runs natively in bf16)
cast their floating inputs down, black-list layers (normalizations, losses,
softmax — numerically f32-sensitive) cast them up, everything else runs in
whatever dtype arrives.  XLA fuses the casts into neighbors, so the policy
costs nothing at runtime.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Set

import jax
import jax.numpy as jnp

from ..framework.errors import InvalidArgumentError

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_state",
           "cast_layer_call", "WHITE_CLASSES", "BLACK_CLASSES"]

# Layer-class names, mirroring fp16_lists.py op groupings
WHITE_CLASSES: Set[str] = {
    "Linear", "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
    "Conv2DTranspose", "Conv3DTranspose", "ColumnParallelLinear",
    "RowParallelLinear", "MultiHeadAttention", "ParallelAttention",
    "BertSelfAttention",
}
BLACK_CLASSES: Set[str] = {
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
    "InstanceNorm3D", "Softmax", "LogSoftmax",
    "CrossEntropyLoss", "NLLLoss", "BCELoss", "BCEWithLogitsLoss",
    "KLDivLoss", "MSELoss", "L1Loss", "SmoothL1Loss",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.white: Set[str] = set()
        self.black: Set[str] = set()


_state = _AmpState()


def amp_state() -> _AmpState:
    return _state


def _cast_floats(tree, dtype):
    def cast(x):
        if isinstance(x, (jax.Array, jnp.ndarray)) and jnp.issubdtype(
                jnp.asarray(x).dtype, jnp.floating):
            return jnp.asarray(x).astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


def _policy_dtype(layer) -> Optional[object]:
    """The dtype this layer's floats should compute in, or None (no cast)."""
    if not _state.enabled:
        return None
    name = type(layer).__name__
    if name in _state.black:
        return jnp.float32
    if name in _state.white or _state.level == "O2":
        return _state.dtype
    return None


@contextlib.contextmanager
def cast_layer_call(layer, args, kwargs):
    """Called from Layer.__call__: apply the active autocast policy to the
    inputs AND the layer's own parameters (a bf16 input × f32 weight matmul
    silently promotes back to f32, so weights must be cast too — the
    reference does the same by casting the persistable inputs of each
    white-list op, fp16_utils.py).  Parameter boxes are swapped to cast
    views for the call and restored after; under jit these are free
    converts folded into the dot."""
    dtype = _policy_dtype(layer)
    if dtype is None:
        yield args, kwargs
        return
    args = tuple(_cast_floats(a, dtype) for a in args)
    kwargs = {k: _cast_floats(v, dtype) for k, v in kwargs.items()}
    saved = []
    for box in layer._parameters.values():
        if box is not None and jnp.issubdtype(box.value.dtype, jnp.floating) \
                and box.value.dtype != dtype:
            saved.append((box, box.value))
            box.value = box.value.astype(dtype)
    try:
        yield args, kwargs
    finally:
        for box, v in saved:
            box.value = v


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None,
              custom_black_list=None, level: str = "O1", dtype="bfloat16"):
    """Context manager enabling the mixed-precision policy.

    O1: per-layer white/black lists (default).  O2: everything floating is
    cast to the amp dtype except black-list layers (use with
    ``amp.decorate`` for bf16 parameters + f32 master weights).
    """
    if level not in ("O0", "O1", "O2"):
        raise InvalidArgumentError(f"amp level {level!r} not in O0/O1/O2")
    import numpy as np

    dt = jnp.bfloat16 if str(dtype) in ("bfloat16", "bf16") else jnp.float16
    prev = (_state.enabled, _state.dtype, _state.level, _state.white, _state.black)
    white = set(WHITE_CLASSES) | set(custom_white_list or ())
    black = (set(BLACK_CLASSES) - set(custom_white_list or ())) | set(custom_black_list or ())
    white -= set(custom_black_list or ())
    _state.enabled = enable and level != "O0"
    _state.dtype = dt
    _state.level = level
    _state.white = white
    _state.black = black
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level,
         _state.white, _state.black) = prev


amp_guard = auto_cast  # legacy alias (fluid/dygraph/amp/auto_cast.py)


def decorate(models=None, optimizers=None, level: str = "O2",
             dtype="bfloat16", master_weight: Optional[bool] = None,
             save_dtype=None):
    """O2 preparation (parity: paddle.amp.decorate): cast model params to the
    amp dtype and enable f32 master weights in the optimizer."""
    from ..nn.layer_base import Layer
    from ..optimizer.optimizer import Optimizer

    if level != "O2":
        return (models, optimizers) if optimizers is not None else models
    nets = models if isinstance(models, (list, tuple)) else [models]
    for net in nets:
        if isinstance(net, Layer):
            net.astype(str(dtype))
    opts = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
    for opt in opts:
        if isinstance(opt, Optimizer) and master_weight is not False:
            opt._multi_precision = True
    if optimizers is None:
        return models
    return models, optimizers

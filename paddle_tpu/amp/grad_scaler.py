"""GradScaler — dynamic loss scaling for fp16 training.

Parity: paddle.amp.GradScaler (reference: python/paddle/amp/grad_scaler.py
wrapping fluid/dygraph/amp/loss_scaler.py:27 AmpScaler; C++ state machine
operators/amp/update_loss_scaling_op.cc: scale ×2 after
``incr_every_n_steps`` finite steps, ×0.5 after
``decr_every_n_nan_or_inf`` non-finite steps, skipping updates on inf).

bf16 training does not need loss scaling (f32 exponent range) — construct
with ``enable=False`` or just don't use a scaler; this class exists for
fp16 parity and for workloads ported from GPU recipes.

Both eager (``scale``/``step``/``update``) and functional/jit
(``unscale_and_check``/``apply_state``) forms are provided; the functional
form keeps the finite-check on device so the whole guarded update stays in
one XLA program (the reference's check_finite_and_unscale + conditional
update ops, fused).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..framework.errors import InvalidArgumentError

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0 ** 15,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000, decr_every_n_nan_or_inf: int = 2,
                 use_dynamic_loss_scaling: bool = True):
        if incr_ratio <= 1.0 or not (0.0 < decr_ratio < 1.0):
            raise InvalidArgumentError("incr_ratio>1 and 0<decr_ratio<1 required")
        self._enable = enable
        self._init_scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._state = self.init_state()
        self._skipped_steps = 0  # inf/nan steps skipped by eager step()

    # -- functional core -----------------------------------------------------
    def init_state(self) -> Dict[str, jax.Array]:
        return {
            "scale": jnp.asarray(self._init_scale, jnp.float32),
            "good_steps": jnp.zeros((), jnp.int32),
            "bad_steps": jnp.zeros((), jnp.int32),
        }

    def scale_value(self, state) -> jax.Array:
        return state["scale"]

    def unscale_and_check(self, grads, state) -> Tuple[Any, jax.Array]:
        """Divide grads by the scale; return (unscaled, found_inf[bool])."""
        inv = 1.0 / state["scale"]
        unscaled = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)
        leaves = jax.tree_util.tree_leaves(unscaled)
        finite = jnp.asarray(True)
        for g in leaves:
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
        return unscaled, jnp.logical_not(finite)

    def next_state(self, state, found_inf) -> Dict[str, jax.Array]:
        """The update_loss_scaling_op state machine, branch-free."""
        if not self._dynamic:
            return state
        good = jnp.where(found_inf, 0, state["good_steps"] + 1)
        bad = jnp.where(found_inf, state["bad_steps"] + 1, 0)
        grow = good >= self._incr_every
        shrink = bad >= self._decr_every
        scale = state["scale"]
        scale = jnp.where(grow, scale * self._incr_ratio, scale)
        scale = jnp.where(shrink, jnp.maximum(scale * self._decr_ratio, 1.0), scale)
        good = jnp.where(grow, 0, good)
        bad = jnp.where(shrink, 0, bad)
        return {"scale": scale, "good_steps": good, "bad_steps": bad}

    def guarded_update(self, optimizer, grads, opt_state, params, state, lr=None):
        """Jit-safe: unscale, check, update-or-skip, advance scaler state.
        Returns (new_params, new_opt_state, new_scaler_state, found_inf)."""
        unscaled, found_inf = self.unscale_and_check(grads, state)
        new_params, new_opt = optimizer.update(unscaled, opt_state, params, lr=lr)
        # skip: keep old values where the step was non-finite
        pick = lambda new, old: jax.tree_util.tree_map(
            lambda n, o: jnp.where(found_inf, o, n), new, old)
        new_params = pick(new_params, params)
        new_opt = pick(new_opt, opt_state)
        return new_params, new_opt, self.next_state(state, found_inf), found_inf

    # -- eager API (paddle dygraph flow) -------------------------------------
    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._dynamic

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._state["scale"].astype(jnp.asarray(loss).dtype)

    def step(self, optimizer, grads=None):
        """Unscale grads, skip the step on inf/nan (eager host check)."""
        if not self._enable:
            optimizer.step(grads)
            return
        if grads is None:
            raise InvalidArgumentError("step() needs grads (no implicit tape)")
        # materialize ONCE — a generator input would otherwise yield keys
        # and then an empty vals list (silent no-op step)
        is_dict = isinstance(grads, dict)
        items = list(grads.items()) if is_dict else list(enumerate(grads))
        keys = [k for k, _ in items]
        vals = [v for _, v in items]
        unscaled, found_inf = self.unscale_and_check(vals, self._state)
        self._found_inf = bool(found_inf)
        if self._found_inf:
            self._skipped_steps += 1
        else:
            out = dict(zip(keys, unscaled)) if is_dict else list(unscaled)
            optimizer.step(out)
        self._publish()

    def update(self):
        if self._enable and self._dynamic:
            self._state = jax.tree_util.tree_map(
                jnp.asarray,
                self.next_state(self._state, jnp.asarray(getattr(self, "_found_inf", False))),
            )
            self._publish()

    def _publish(self) -> None:
        """Snapshot scale + skip counters onto the trace-events bus as an
        ``("amp", "grad_scaler")`` event — latest value wins at consumers
        (RetraceMonitor.amp_stats).  Gated on an active observer so the
        common no-dashboard path pays one falsy check, no device syncs."""
        from ..framework import trace_events

        if not trace_events.active():
            return
        trace_events.notify(("amp", "grad_scaler"), {
            "scale": float(self._state["scale"]),
            "skipped_steps": int(self._skipped_steps),
            "good_steps": int(self._state["good_steps"]),
            "bad_steps": int(self._state["bad_steps"]),
        })

    def minimize(self, optimizer, scaled_loss=None, grads=None):
        self.step(optimizer, grads)
        self.update()

    # -- introspection / persistence -----------------------------------------
    def get_loss_scaling(self) -> float:
        return float(self._state["scale"])

    def set_init_loss_scaling(self, v: float):
        self._state["scale"] = jnp.asarray(float(v), jnp.float32)

    def state_dict(self):
        return {k: jax.device_get(v) for k, v in self._state.items()} | {
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
        }

    def load_state_dict(self, state):
        for k in ("scale", "good_steps", "bad_steps"):
            if k in state:
                self._state[k] = jnp.asarray(state[k])

    set_state_dict = load_state_dict


AmpScaler = GradScaler  # legacy alias (fluid/dygraph/amp/loss_scaler.py)

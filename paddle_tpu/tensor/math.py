"""Elementwise & reduction math ops.

Parity surface: python/paddle/tensor/math.py plus the reference's
elementwise_* / reduce_ops operator families
(paddle/fluid/operators/elementwise/, operators/reduce_ops/).  On TPU all of
these lower to single XLA HLO ops that the compiler fuses into neighbors, so
there is no per-op kernel code — the value here is the paddle-parity calling
convention (names, default dtypes, broadcasting semantics).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import dtype as _dt

__all__ = [
    # elementwise binary
    "add", "add_n", "addcmul", "subtract", "multiply", "divide", "floor_divide", "mod", "floor_mod", "remainder",
    "pow", "maximum", "minimum", "fmax", "fmin", "atan2", "logaddexp",
    "heaviside", "gcd", "lcm", "hypot", "copysign", "nextafter", "ldexp",
    # elementwise unary
    "abs", "neg", "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt",
    "rsqrt", "square", "reciprocal", "sign", "floor", "ceil", "round", "trunc",
    "frac", "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
    "tanh", "asinh", "acosh", "atanh", "erf", "erfinv", "sigmoid", "logit",
    "digamma", "lgamma", "angle", "conj", "deg2rad", "rad2deg", "exp2",
    "i0", "i0e", "i1", "i1e", "sgn",
    # scale/clip
    "scale", "clip", "stanh",
    # reductions
    "sum", "nansum", "mean", "nanmean", "prod", "max", "min", "amax", "amin",
    "logsumexp", "all", "any", "count_nonzero",
    # cumulative
    "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp",
    # misc
    "addmm", "inner", "outer", "multiplex", "lerp", "diff", "trapezoid",
    "isfinite", "isinf", "isnan", "nan_to_num", "broadcast_shape",
    "increment", "kron", "renorm", "trace", "diagonal", "take",
]


def _f(x):
    """Promote python scalars / int arrays to the default float dtype."""
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.floating) and not jnp.issubdtype(x.dtype, jnp.complexfloating):
        x = x.astype(_dt.get_default_dtype())
    return x


# -- elementwise binary ------------------------------------------------------

def add(x, y, name=None):
    return jnp.add(x, y)


def subtract(x, y, name=None):
    return jnp.subtract(x, y)


def multiply(x, y, name=None):
    return jnp.multiply(x, y)


def divide(x, y, name=None):
    return jnp.true_divide(x, y)


def floor_divide(x, y, name=None):
    return jnp.floor_divide(x, y)


def mod(x, y, name=None):
    return jnp.mod(x, y)


remainder = mod
floor_mod = mod  # legacy alias (ref: tensor/math.py floor_mod == elementwise_mod)


def pow(x, y, name=None):
    return jnp.power(x, y)


def maximum(x, y, name=None):
    return jnp.maximum(x, y)


def minimum(x, y, name=None):
    return jnp.minimum(x, y)


def fmax(x, y, name=None):
    return jnp.fmax(x, y)


def fmin(x, y, name=None):
    return jnp.fmin(x, y)


def atan2(x, y, name=None):
    return jnp.arctan2(_f(x), _f(y))


def logaddexp(x, y, name=None):
    return jnp.logaddexp(_f(x), _f(y))


def heaviside(x, y, name=None):
    return jnp.heaviside(x, y)


def gcd(x, y, name=None):
    return jnp.gcd(x, y)


def lcm(x, y, name=None):
    return jnp.lcm(x, y)


def hypot(x, y, name=None):
    return jnp.hypot(_f(x), _f(y))


def copysign(x, y, name=None):
    return jnp.copysign(x, y)


def nextafter(x, y, name=None):
    return jnp.nextafter(x, y)


def ldexp(x, y, name=None):
    return jnp.ldexp(x, y)


# -- elementwise unary -------------------------------------------------------

def abs(x, name=None):
    return jnp.abs(x)


def neg(x, name=None):
    return jnp.negative(x)


def exp(x, name=None):
    return jnp.exp(_f(x))


def expm1(x, name=None):
    return jnp.expm1(_f(x))


def exp2(x, name=None):
    return jnp.exp2(_f(x))


def log(x, name=None):
    return jnp.log(_f(x))


def log2(x, name=None):
    return jnp.log2(_f(x))


def log10(x, name=None):
    return jnp.log10(_f(x))


def log1p(x, name=None):
    return jnp.log1p(_f(x))


def sqrt(x, name=None):
    return jnp.sqrt(_f(x))


def rsqrt(x, name=None):
    return jax.lax.rsqrt(_f(x))


def square(x, name=None):
    return jnp.square(x)


def reciprocal(x, name=None):
    return jnp.reciprocal(_f(x))


def sign(x, name=None):
    return jnp.sign(x)


def sgn(x, name=None):
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0, x / jnp.where(mag == 0, 1, mag))
    return jnp.sign(x)


def floor(x, name=None):
    return jnp.floor(x)


def ceil(x, name=None):
    return jnp.ceil(x)


def round(x, name=None):
    return jnp.round(x)


def trunc(x, name=None):
    return jnp.trunc(x)


def frac(x, name=None):
    return jnp.asarray(x) - jnp.trunc(x)


def sin(x, name=None):
    return jnp.sin(_f(x))


def cos(x, name=None):
    return jnp.cos(_f(x))


def tan(x, name=None):
    return jnp.tan(_f(x))


def asin(x, name=None):
    return jnp.arcsin(_f(x))


def acos(x, name=None):
    return jnp.arccos(_f(x))


def atan(x, name=None):
    return jnp.arctan(_f(x))


def sinh(x, name=None):
    return jnp.sinh(_f(x))


def cosh(x, name=None):
    return jnp.cosh(_f(x))


def tanh(x, name=None):
    return jnp.tanh(_f(x))


def asinh(x, name=None):
    return jnp.arcsinh(_f(x))


def acosh(x, name=None):
    return jnp.arccosh(_f(x))


def atanh(x, name=None):
    return jnp.arctanh(_f(x))


def erf(x, name=None):
    return jax.scipy.special.erf(_f(x))


def erfinv(x, name=None):
    return jax.scipy.special.erfinv(_f(x))


def sigmoid(x, name=None):
    return jax.nn.sigmoid(_f(x))


def logit(x, eps=None, name=None):
    x = _f(x)
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jax.scipy.special.logit(x)


def digamma(x, name=None):
    return jax.scipy.special.digamma(_f(x))


def lgamma(x, name=None):
    return jax.scipy.special.gammaln(_f(x))


def angle(x, name=None):
    return jnp.angle(x)


def conj(x, name=None):
    return jnp.conj(x)


def deg2rad(x, name=None):
    return jnp.deg2rad(_f(x))


def rad2deg(x, name=None):
    return jnp.rad2deg(_f(x))


def i0(x, name=None):
    return jax.scipy.special.i0(_f(x))


def i0e(x, name=None):
    return jax.scipy.special.i0e(_f(x))


def i1(x, name=None):
    return jax.scipy.special.i1(_f(x))


def i1e(x, name=None):
    return jax.scipy.special.i1e(_f(x))


# -- scale/clip --------------------------------------------------------------

def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """Parity: paddle.scale (ref op: paddle/fluid/operators/scale_op.cc)."""
    x = jnp.asarray(x)
    s = jnp.asarray(scale, x.dtype)
    b = jnp.asarray(bias, x.dtype)
    out = x * s + b if bias_after_scale else (x + b) * s
    if act == "relu":
        out = jax.nn.relu(out)
    elif act == "tanh":
        out = jnp.tanh(out)
    elif act == "sigmoid":
        out = jax.nn.sigmoid(out)
    return out


def clip(x, min=None, max=None, name=None):
    return jnp.clip(x, min, max)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * _f(x))


# -- reductions --------------------------------------------------------------

def _axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return axis


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    return jnp.sum(x, axis=_axis(axis), dtype=_dt.convert_dtype(dtype) if dtype else None, keepdims=keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return jnp.nansum(x, axis=_axis(axis), dtype=_dt.convert_dtype(dtype) if dtype else None, keepdims=keepdim)


def mean(x, axis=None, keepdim=False, name=None):
    return jnp.mean(_f(x), axis=_axis(axis), keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False, name=None):
    return jnp.nanmean(_f(x), axis=_axis(axis), keepdims=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return jnp.prod(x, axis=_axis(axis), dtype=_dt.convert_dtype(dtype) if dtype else None, keepdims=keepdim)


def max(x, axis=None, keepdim=False, name=None):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


def min(x, axis=None, keepdim=False, name=None):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


amax = max
amin = min


def logsumexp(x, axis=None, keepdim=False, name=None):
    return jax.scipy.special.logsumexp(_f(x), axis=_axis(axis), keepdims=keepdim)


def all(x, axis=None, keepdim=False, name=None):
    return jnp.all(x, axis=_axis(axis), keepdims=keepdim)


def any(x, axis=None, keepdim=False, name=None):
    return jnp.any(x, axis=_axis(axis), keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim)


# -- cumulative --------------------------------------------------------------

def cumsum(x, axis=None, dtype=None, name=None):
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=_dt.convert_dtype(dtype) if dtype else None)


def cumprod(x, dim=None, dtype=None, name=None):
    return jnp.cumprod(x, axis=dim, dtype=_dt.convert_dtype(dtype) if dtype else None)


def cummax(x, axis=None, dtype="int64", name=None):
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    values = jax.lax.cummax(x, axis=axis)
    idx_dtype = _dt.convert_dtype(dtype)
    n = x.shape[axis]
    iota = jax.lax.broadcasted_iota(jnp.int32, x.shape, axis)
    is_new = x == values
    indices = jax.lax.cummax(jnp.where(is_new, iota, -1), axis=axis)
    return values, indices.astype(idx_dtype)


def cummin(x, axis=None, dtype="int64", name=None):
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    values = jax.lax.cummin(x, axis=axis)
    idx_dtype = _dt.convert_dtype(dtype)
    iota = jax.lax.broadcasted_iota(jnp.int32, x.shape, axis)
    is_new = x == values
    indices = jax.lax.cummax(jnp.where(is_new, iota, -1), axis=axis)
    return values, indices.astype(idx_dtype)


def logcumsumexp(x, axis=None, name=None):
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    return jax.lax.cumlogsumexp(_f(x), axis=axis)


# -- misc --------------------------------------------------------------------

def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return beta * jnp.asarray(input) + alpha * jnp.matmul(x, y)


def inner(x, y, name=None):
    return jnp.inner(x, y)


def outer(x, y, name=None):
    return jnp.outer(x, y)


def multiplex(inputs, index, name=None):
    """Parity: paddle.multiplex (ref op: operators/multiplex_op.cc)."""
    stacked = jnp.stack(inputs, axis=0)  # (n, batch, ...)
    idx = jnp.reshape(jnp.asarray(index), (-1,))
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]


def lerp(x, y, weight, name=None):
    x = _f(x)
    return x + jnp.asarray(weight, x.dtype) * (jnp.asarray(y, x.dtype) - x)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if dx is None and x is None:
        dx = 1.0
    return jnp.trapezoid(_f(y), x=x, dx=dx if dx is not None else 1.0, axis=axis)


def isfinite(x, name=None):
    return jnp.isfinite(x)


def isinf(x, name=None):
    return jnp.isinf(x)


def isnan(x, name=None):
    return jnp.isnan(x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def broadcast_shape(x_shape, y_shape):
    import numpy as np

    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def increment(x, value=1.0, name=None):
    """Functional: returns x + value (XLA has no in-place mutation)."""
    x = jnp.asarray(x)
    return x + jnp.asarray(value, x.dtype)


def kron(x, y, name=None):
    return jnp.kron(x, y)


def renorm(x, p, axis, max_norm, name=None):
    x = _f(x)
    dims = tuple(i for i in range(x.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(x) ** p, axis=dims, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def take(x, index, mode="raise", name=None):
    x = jnp.asarray(x).ravel()
    idx = jnp.asarray(index)
    if mode == "wrap":
        idx = jnp.mod(idx, x.shape[0])
    elif mode == "clip":
        idx = jnp.clip(idx, -x.shape[0], x.shape[0] - 1)
    idx = jnp.where(idx < 0, idx + x.shape[0], idx)
    return x[idx]

def add_n(inputs, name=None):
    """Elementwise sum of a tensor list (ref: tensor/math.py:721 sum op)."""
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    out = jnp.asarray(inputs[0])
    for t in inputs[1:]:
        out = out + jnp.asarray(t)
    return out


def addcmul(input, tensor1, tensor2, value=1.0, name=None):
    """out = input + value * tensor1 * tensor2 (ref: tensor/math.py:1318)."""
    return jnp.asarray(input) + value * jnp.asarray(tensor1) * jnp.asarray(tensor2)

"""Tensor attribute helpers + einsum.

Parity surface: python/paddle/tensor/attribute.py, einsum.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["shape", "rank", "is_complex", "is_floating_point", "is_integer", "einsum"]


def shape(x, name=None):
    return jnp.asarray(jnp.shape(x), dtype=jnp.int32)


def rank(x, name=None):
    return jnp.asarray(jnp.ndim(x))


def is_complex(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer)


def einsum(equation, *operands):
    """Parity: paddle.einsum — maps straight to XLA dot_general chains."""
    return jnp.einsum(equation, *operands)

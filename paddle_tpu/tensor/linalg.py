"""Linear algebra ops.

Parity surface: python/paddle/tensor/linalg.py and the reference's
matmul/mul ops (paddle/fluid/operators/matmul_op.cc, math/blas.h cuBLAS
wrapper).  On TPU every matmul maps to the MXU via a single XLA dot_general —
the entire Blas wrapper layer of the reference collapses into
``jax.lax.dot_general`` with an appropriate ``preferred_element_type``
(float32 accumulation for bf16 inputs, matching cuBLAS tensor-op math mode).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import dtype as _dt

__all__ = [
    "matmul", "dot", "mm", "bmm", "mv", "t", "transpose_", "norm", "dist",
    "cond", "cov", "corrcoef", "cholesky", "cholesky_solve", "inverse", "det",
    "slogdet", "matrix_rank", "matrix_power", "qr", "lu", "svd", "pinv",
    "solve", "triangular_solve", "lstsq", "eig", "eigh", "eigvals", "eigvalsh",
    "multi_dot", "cross", "histogram", "bincount", "householder_product",
    "matrix_exp", "pca_lowrank",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """Parity: paddle.matmul (ref: operators/matmul_op.cc).

    bf16 inputs accumulate in f32 on the MXU (preferred_element_type), which
    matches the reference's cuBLAS CUBLAS_COMPUTE_32F on tensor cores.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    if x.dtype == _dt.bfloat16 or y.dtype == _dt.bfloat16:
        return jnp.matmul(x, y, preferred_element_type=jnp.float32).astype(
            jnp.result_type(x.dtype, y.dtype)
        )
    return jnp.matmul(x, y)


def dot(x, y, name=None):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if x.ndim == 2:
        return jnp.sum(x * y, axis=-1)
    return jnp.dot(x, y)


def mm(input, mat2, name=None):
    return jnp.matmul(input, mat2)


def bmm(x, y, name=None):
    return jnp.matmul(x, y)


def mv(x, vec, name=None):
    return jnp.matmul(x, vec)


def t(input, name=None):
    x = jnp.asarray(input)
    if x.ndim < 2:
        return x
    return jnp.swapaxes(x, -1, -2)


def transpose_(x, perm, name=None):
    return jnp.transpose(x, axes=perm)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(_dt.get_default_dtype())
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x), keepdims=keepdim))
        return jnp.linalg.norm(x, ord="fro" if isinstance(axis, (tuple, list)) else None,
                               axis=tuple(axis) if isinstance(axis, (tuple, list)) else axis,
                               keepdims=keepdim)
    if p == "nuc":
        return jnp.linalg.norm(x, ord="nuc", axis=tuple(axis), keepdims=keepdim)
    if axis is None:
        x = x.ravel()
        axis = 0
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis if not isinstance(axis, list) else tuple(axis), keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis if not isinstance(axis, list) else tuple(axis), keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    ax = tuple(axis) if isinstance(axis, (tuple, list)) else axis
    return jnp.sum(jnp.abs(x) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)


def dist(x, y, p=2, name=None):
    return norm(jnp.asarray(x) - jnp.asarray(y), p=p)


def cond(x, p=None, name=None):
    return jnp.linalg.cond(x, p=p)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fweights, aweights=aweights)


def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(x, rowvar=rowvar)


def cholesky(x, upper=False, name=None):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


def cholesky_solve(x, y, upper=False, name=None):
    return jax.scipy.linalg.cho_solve((jnp.asarray(y), not upper), jnp.asarray(x))


def inverse(x, name=None):
    return jnp.linalg.inv(x)


def det(x, name=None):
    return jnp.linalg.det(x)


def slogdet(x, name=None):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


def qr(x, mode="reduced", name=None):
    return jnp.linalg.qr(x, mode=mode)


def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = jax.scipy.linalg.lu_factor(jnp.asarray(x))
    if get_infos:
        return lu_, piv.astype(jnp.int32) + 1, jnp.zeros((), jnp.int32)
    return lu_, piv.astype(jnp.int32) + 1


def svd(x, full_matrices=False, name=None):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return jax.scipy.linalg.solve_triangular(
        jnp.asarray(x), jnp.asarray(y), lower=not upper,
        trans=1 if transpose else 0, unit_diagonal=unitriangular)


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(jnp.asarray(x), jnp.asarray(y), rcond=rcond)
    return sol, res, rank, sv


def eig(x, name=None):
    """General eig: XLA supports it on CPU only; runs via host callback there."""
    import numpy as np

    w, v = np.linalg.eig(np.asarray(x))
    return jnp.asarray(w), jnp.asarray(v)


def eigh(x, UPLO="L", name=None):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvals(x, name=None):
    import numpy as np

    return jnp.asarray(np.linalg.eigvals(np.asarray(x)))


def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def multi_dot(x, name=None):
    return jnp.linalg.multi_dot(list(x))


def cross(x, y, axis=9, name=None):
    x = jnp.asarray(x)
    if axis == 9:
        # paddle default: first axis with dim 3
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    return jnp.cross(x, jnp.asarray(y), axis=axis)


def histogram(input, bins=100, min=0, max=0, name=None):
    x = jnp.asarray(input).ravel()
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return hist.astype(jnp.int64)


def bincount(x, weights=None, minlength=0, name=None):
    return jnp.bincount(jnp.asarray(x).astype(jnp.int32), weights=weights,
                        minlength=minlength, length=None)


def householder_product(x, tau, name=None):
    import numpy as np
    from scipy.linalg import lapack  # scipy ships with the image

    a = np.asarray(x)
    t = np.asarray(tau)
    q, _, _ = lapack.dorgqr(a.astype(np.float64), t.astype(np.float64))
    return jnp.asarray(q.astype(a.dtype))


def matrix_exp(x, name=None):
    return jax.scipy.linalg.expm(jnp.asarray(x))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    x = jnp.asarray(x)
    m, n = x.shape[-2], x.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        x = x - jnp.mean(x, axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(x, full_matrices=False)
    return u[..., :q], s[..., :q], jnp.swapaxes(vt, -1, -2)[..., :q]

"""Random sampling ops.

Parity surface: python/paddle/tensor/random.py (reference ops:
operators/uniform_random_op.cc, gaussian_random_op.cc, dropout_op.cc seeds,
framework/generator.cc).

Eager calls draw fresh subkeys from the global Generator
(paddle_tpu.framework.random).  Every function also accepts ``key=`` for
pure/traced use — inside jit you MUST pass a key or the randomness freezes
at trace time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import dtype as _dt
from ..framework.random import split_key

__all__ = [
    "uniform", "rand", "randn", "normal", "gaussian", "standard_normal", "randint",
    "randint_like", "randperm", "multinomial", "bernoulli", "poisson",
    "exponential", "uniform_", "normal_",
]


def _dtype(d):
    return _dt.convert_dtype(d) if d is not None else _dt.get_default_dtype()


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None, key=None):
    k = split_key(key) if seed == 0 else jax.random.PRNGKey(seed)
    return jax.random.uniform(k, tuple(shape), dtype=_dtype(dtype), minval=min, maxval=max)


def rand(shape, dtype=None, name=None, key=None):
    return jax.random.uniform(split_key(key), tuple(shape), dtype=_dtype(dtype))


def randn(shape, dtype=None, name=None, key=None):
    return jax.random.normal(split_key(key), tuple(shape), dtype=_dtype(dtype))


def normal(mean=0.0, std=1.0, shape=None, name=None, key=None):
    if isinstance(mean, jax.Array) or isinstance(std, jax.Array):
        shape = jnp.broadcast_shapes(jnp.shape(mean), jnp.shape(std)) if shape is None else tuple(shape)
        z = jax.random.normal(split_key(key), shape, dtype=_dt.get_default_dtype())
        return z * jnp.asarray(std, z.dtype) + jnp.asarray(mean, z.dtype)
    z = jax.random.normal(split_key(key), tuple(shape or ()), dtype=_dt.get_default_dtype())
    return z * std + mean


def standard_normal(shape, dtype=None, name=None, key=None):
    return jax.random.normal(split_key(key), tuple(shape), dtype=_dtype(dtype))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None, key=None):
    if high is None:
        low, high = 0, low
    return jax.random.randint(split_key(key), tuple(shape), low, high, dtype=_dt.convert_dtype(dtype))


def randint_like(x, low=0, high=None, dtype=None, name=None, key=None):
    x = jnp.asarray(x)
    return randint(low, high, x.shape, dtype or x.dtype, key=key)


def randperm(n, dtype="int64", name=None, key=None):
    return jax.random.permutation(split_key(key), n).astype(_dt.convert_dtype(dtype))


def multinomial(x, num_samples=1, replacement=False, name=None, key=None):
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(_dt.get_default_dtype())
    logits = jnp.log(jnp.clip(x / jnp.sum(x, axis=-1, keepdims=True), 1e-30, None))
    k = split_key(key)
    if replacement:
        return jax.random.categorical(k, logits, axis=-1, shape=(num_samples,) + x.shape[:-1]).T.astype(jnp.int64) \
            if x.ndim > 1 else jax.random.categorical(k, logits, shape=(num_samples,)).astype(jnp.int64)
    # without replacement: Gumbel top-k trick
    g = jax.random.gumbel(k, x.shape, dtype=logits.dtype)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return idx.astype(jnp.int64)


def bernoulli(x, name=None, key=None):
    x = jnp.asarray(x)
    u = jax.random.uniform(split_key(key), x.shape, dtype=x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32)
    return (u < x).astype(x.dtype)


def poisson(x, name=None, key=None):
    x = jnp.asarray(x)
    return jax.random.poisson(split_key(key), x, dtype=jnp.int32).astype(x.dtype)


def exponential(x_or_lam=1.0, shape=None, name=None, key=None):
    if shape is None and hasattr(x_or_lam, "shape"):
        x = jnp.asarray(x_or_lam)
        e = jax.random.exponential(split_key(key), x.shape, dtype=x.dtype)
        return e / x
    e = jax.random.exponential(split_key(key), tuple(shape or ()), dtype=_dt.get_default_dtype())
    return e / x_or_lam


# "in-place" aliases: functional on TPU, kept for API-shape parity.
def uniform_(x, min=-1.0, max=1.0, key=None):
    x = jnp.asarray(x)
    return jax.random.uniform(split_key(key), x.shape, dtype=x.dtype, minval=min, maxval=max)


def normal_(x, mean=0.0, std=1.0, key=None):
    x = jnp.asarray(x)
    return jax.random.normal(split_key(key), x.shape, dtype=x.dtype) * std + mean

def gaussian(shape, mean=0.0, std=1.0, dtype=None, name=None, key=None):
    """Gaussian-distributed random tensor (ref: tensor/random.py:155 over
    gaussian_random_op.cc) — samples IN the requested dtype (casting a
    f32 draw would give f32 tail resolution in a f64 output)."""
    z = jax.random.normal(split_key(key), tuple(shape), dtype=_dtype(dtype))
    return z * std + mean


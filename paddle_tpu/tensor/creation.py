"""Tensor creation ops.

Parity surface: python/paddle/tensor/creation.py (reference).  A
``paddle_tpu.Tensor`` IS a ``jax.Array`` — there is no wrapper class.  The
reference's LoDTensor ragged batching (paddle/fluid/framework/lod_tensor.h:114)
is deliberately not reproduced: XLA wants static shapes, so ragged data is
handled by padding + masks at the data-pipeline level (see paddle_tpu.io).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import dtype as _dt
from ..framework.errors import InvalidArgumentError

__all__ = [
    "to_tensor",
    "zeros",
    "ones",
    "full",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty",
    "empty_like",
    "arange",
    "linspace",
    "logspace",
    "eye",
    "meshgrid",
    "diag",
    "diagflat",
    "tril",
    "triu",
    "tril_indices",
    "triu_indices",
    "assign",
    "clone",
    "complex",
    "real",
    "imag",
    "numel",
    "one_hot",
]


def _resolve(dtype):
    return _dt.convert_dtype(dtype) if dtype is not None else None


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """Parity: ``paddle.to_tensor``. Returns a jax.Array on the current device.

    ``stop_gradient`` is accepted for API parity; differentiation in this
    framework is functional (jax.grad), so the flag is a no-op.
    """
    del stop_gradient
    if isinstance(data, (list, tuple)) and any(
        isinstance(x, jax.Array) for x in jax.tree_util.tree_leaves(data)
    ):
        data = jnp.asarray(data)
    arr = jnp.asarray(data, dtype=_resolve(dtype))
    if arr.dtype == jnp.float64 and dtype is None:
        # numpy default float64 → framework default float, like paddle
        arr = arr.astype(_dt.get_default_dtype())
    if place is not None:
        arr = jax.device_put(arr, place.jax_device() if hasattr(place, "jax_device") else place)
    return arr


def zeros(shape, dtype=None):
    return jnp.zeros(shape, dtype=_resolve(dtype) or _dt.get_default_dtype())


def ones(shape, dtype=None):
    return jnp.ones(shape, dtype=_resolve(dtype) or _dt.get_default_dtype())


def full(shape, fill_value, dtype=None):
    # paddle.full defaults to the framework float dtype regardless of the
    # python type of fill_value
    if dtype is None and not isinstance(fill_value, bool) and isinstance(fill_value, (int, float)):
        return jnp.full(shape, fill_value, dtype=_dt.get_default_dtype())
    return jnp.full(shape, fill_value, dtype=_resolve(dtype) if dtype is not None else None)


def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=_resolve(dtype))


def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=_resolve(dtype))


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=_resolve(dtype))


def empty(shape, dtype=None):
    # XLA has no uninitialized buffers; zeros compiles to a cheap broadcast.
    return zeros(shape, dtype)


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, dtype=_resolve(dtype))


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, int(num), dtype=_resolve(dtype) or _dt.get_default_dtype())


def logspace(start, stop, num, base=10.0, dtype=None):
    return jnp.logspace(start, stop, int(num), base=base, dtype=_resolve(dtype) or _dt.get_default_dtype())


def eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=_resolve(dtype) or _dt.get_default_dtype())


def meshgrid(*args):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return list(jnp.meshgrid(*args, indexing="ij"))


def diag(x, offset=0, padding_value=0):
    x = jnp.asarray(x)
    if x.ndim == 1 and padding_value != 0:
        d = jnp.diag(x, k=offset)
        mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
        return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
    return jnp.diag(x, k=offset)


def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def tril_indices(row, col, offset=0):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return jnp.stack([r, c])


def triu_indices(row, col=None, offset=0):
    r, c = jnp.triu_indices(row, k=offset, m=col if col is not None else row)
    return jnp.stack([r, c])


def assign(x, output=None):
    """Parity: ``paddle.assign``. Functional: returns a copy; ``output`` ignored
    (XLA buffers are immutable — in-place assign does not exist on TPU)."""
    del output
    return jnp.asarray(x).copy() if isinstance(x, jax.Array) else jnp.asarray(np.asarray(x))


def clone(x):
    return jnp.asarray(x).copy()


def complex(real_, imag_):
    return jax.lax.complex(jnp.asarray(real_), jnp.asarray(imag_))


def real(x):
    return jnp.real(x)


def imag(x):
    return jnp.imag(x)


def numel(x):
    return jnp.asarray(jnp.size(x))


def one_hot(x, num_classes):
    if num_classes <= 0:
        raise InvalidArgumentError("num_classes must be > 0")
    return jax.nn.one_hot(jnp.asarray(x), num_classes, dtype=_dt.get_default_dtype())

"""Tensor printing — set_printoptions / to_string.

Parity: python/paddle/tensor/to_string.py (print options held in a
DEFAULT_PRINT_OPTIONS struct consumed by _to_summary).  Arrays here ARE
jax arrays whose repr goes through numpy, so the options map onto
numpy's printoptions.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["set_printoptions", "to_string"]


@dataclass
class _PrintOptions:
    precision: int = 8
    threshold: int = 1000
    edgeitems: int = 3
    sci_mode: bool = False
    linewidth: int = 80


_options = _PrintOptions()


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Configure tensor formatting (ref: to_string.py set_printoptions).

    Applies to numpy's GLOBAL printoptions too: tensors here are jax
    arrays whose ``repr``/``print`` go through numpy, so this is what
    makes ``print(tensor)`` honor the options — not just ``to_string``.
    """
    if precision is not None:
        _options.precision = int(precision)
    if threshold is not None:
        _options.threshold = int(threshold)
    if edgeitems is not None:
        _options.edgeitems = int(edgeitems)
    if sci_mode is not None:
        _options.sci_mode = bool(sci_mode)
    if linewidth is not None:
        _options.linewidth = int(linewidth)
    np.set_printoptions(precision=_options.precision,
                        threshold=_options.threshold,
                        edgeitems=_options.edgeitems,
                        linewidth=_options.linewidth,
                        suppress=not _options.sci_mode)


def to_string(x, prefix="Tensor"):
    arr = np.asarray(x)
    with np.printoptions(precision=_options.precision,
                         threshold=_options.threshold,
                         edgeitems=_options.edgeitems,
                         linewidth=_options.linewidth,
                         suppress=not _options.sci_mode):
        body = np.array2string(arr, separator=", ")
    return (f"{prefix}(shape={list(arr.shape)}, dtype={arr.dtype},\n"
            f"       {body})")

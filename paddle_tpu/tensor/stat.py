"""Statistics ops.

Parity surface: python/paddle/tensor/stat.py (mean/std/var/quantile...).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework import dtype as _dt

__all__ = ["mean", "std", "var", "numel", "quantile", "nanquantile", "histogramdd"]


def _f(x):
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(_dt.get_default_dtype())
    return x


def mean(x, axis=None, keepdim=False, name=None):
    return jnp.mean(_f(x), axis=tuple(axis) if isinstance(axis, list) else axis, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.std(_f(x), axis=tuple(axis) if isinstance(axis, list) else axis,
                   ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.var(_f(x), axis=tuple(axis) if isinstance(axis, list) else axis,
                   ddof=1 if unbiased else 0, keepdims=keepdim)


def numel(x, name=None):
    return jnp.asarray(jnp.size(x))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return jnp.quantile(_f(x), jnp.asarray(q), axis=axis, keepdims=keepdim, method=interpolation)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return jnp.nanquantile(_f(x), jnp.asarray(q), axis=axis, keepdims=keepdim, method=interpolation)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    import numpy as np

    hist, edges = np.histogramdd(np.asarray(x), bins=bins, range=ranges,
                                 density=density, weights=None if weights is None else np.asarray(weights))
    return jnp.asarray(hist), [jnp.asarray(e) for e in edges]

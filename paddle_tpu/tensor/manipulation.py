"""Shape/layout manipulation ops.

Parity surface: python/paddle/tensor/manipulation.py and the reference ops
reshape/transpose/concat/split/gather/scatter/... (paddle/fluid/operators/).
All are pure XLA metadata or data-movement ops; the compiler fuses or
eliminates most of them.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp

from ..framework import dtype as _dt
from ..framework.errors import InvalidArgumentError

__all__ = [
    "reshape", "flatten", "squeeze", "unsqueeze", "transpose", "moveaxis",
    "concat", "stack", "unstack", "split", "chunk", "tile", "expand",
    "expand_as", "broadcast_to", "broadcast_tensors", "flip", "reverse", "rot90", "roll",
    "gather", "gather_nd", "scatter", "scatter_nd", "scatter_nd_add",
    "index_select", "index_sample", "index_add", "index_put", "put_along_axis",
    "take_along_axis", "slice", "strided_slice", "crop", "pad", "cast",
    "repeat_interleave", "unbind", "unique", "unique_consecutive",
    "masked_select", "masked_fill", "as_complex", "as_real", "view", "view_as",
    "tensordot", "atleast_1d", "atleast_2d", "atleast_3d", "tolist",
    "shard_index", "tensor_split", "hsplit", "vsplit", "dsplit",
    "hstack", "vstack", "dstack", "column_stack", "row_stack",
]


def reshape(x, shape, name=None):
    return jnp.reshape(x, tuple(shape) if not isinstance(shape, int) else (shape,))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return jnp.reshape(x, tuple(shape_or_dtype))
    return jnp.asarray(x).view(_dt.convert_dtype(shape_or_dtype))


def view_as(x, other, name=None):
    return jnp.reshape(x, jnp.shape(other))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = jnp.asarray(x)
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    s = start_axis % nd
    e = stop_axis % nd
    if s > e:
        raise InvalidArgumentError("start_axis must be <= stop_axis")
    new_shape = x.shape[:s] + (-1,) + x.shape[e + 1:]
    return jnp.reshape(x, new_shape)


def squeeze(x, axis=None, name=None):
    x = jnp.asarray(x)
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, int):
        axis = [axis]
    axis = tuple(a % x.ndim for a in axis if x.shape[a % x.ndim] == 1)
    return jnp.squeeze(x, axis=axis) if axis else x


def unsqueeze(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.expand_dims(x, tuple(axis))


def transpose(x, perm=None, name=None):
    return jnp.transpose(x, axes=perm)


def moveaxis(x, source, destination, name=None):
    return jnp.moveaxis(x, source, destination)


def concat(x, axis=0, name=None):
    if isinstance(axis, jax.Array):
        axis = int(axis)
    return jnp.concatenate(list(x), axis=axis)


def stack(x, axis=0, name=None):
    return jnp.stack(list(x), axis=axis)


def unstack(x, axis=0, num=None, name=None):
    x = jnp.asarray(x)
    n = num if num is not None else x.shape[axis]
    return [jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis)]


def split(x, num_or_sections, axis=0, name=None):
    x = jnp.asarray(x)
    if isinstance(axis, jax.Array):
        axis = int(axis)
    if isinstance(num_or_sections, int):
        return list(jnp.split(x, num_or_sections, axis=axis))
    sections = list(num_or_sections)
    total = x.shape[axis]
    if any(s == -1 for s in sections):
        known = builtins.sum(s for s in sections if s != -1)
        sections = [total - known if s == -1 else s for s in sections]
    idx = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        idx.append(acc)
    return list(jnp.split(x, idx, axis=axis))


def chunk(x, chunks, axis=0, name=None):
    return list(jnp.array_split(jnp.asarray(x), chunks, axis=axis))


def tensor_split(x, num_or_indices, axis=0, name=None):
    return list(jnp.array_split(jnp.asarray(x), num_or_indices, axis=axis))


def hsplit(x, num_or_indices, name=None):
    return list(jnp.hsplit(jnp.asarray(x), num_or_indices))


def vsplit(x, num_or_indices, name=None):
    return list(jnp.vsplit(jnp.asarray(x), num_or_indices))


def dsplit(x, num_or_indices, name=None):
    return list(jnp.dsplit(jnp.asarray(x), num_or_indices))


def hstack(x, name=None):
    return jnp.hstack(list(x))


def vstack(x, name=None):
    return jnp.vstack(list(x))


def dstack(x, name=None):
    return jnp.dstack(list(x))


def column_stack(x, name=None):
    return jnp.column_stack(list(x))


row_stack = vstack


def tile(x, repeat_times, name=None):
    return jnp.tile(x, tuple(repeat_times))


def expand(x, shape, name=None):
    x = jnp.asarray(x)
    shape = tuple(
        x.shape[i - (len(shape) - x.ndim)] if s in (-1,) else s
        for i, s in enumerate(shape)
    )
    return jnp.broadcast_to(x, shape)


def expand_as(x, y, name=None):
    return jnp.broadcast_to(x, jnp.shape(y))


def broadcast_to(x, shape, name=None):
    return jnp.broadcast_to(x, tuple(shape))


def broadcast_tensors(inputs, name=None):
    return list(jnp.broadcast_arrays(*inputs))


def reverse(x, axis, name=None):
    """Legacy alias of flip (ref: fluid/layers/tensor.py reverse —
    paddle.reverse / paddle.tensor.reverse)."""
    return flip(x, axis)


def flip(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


def rot90(x, k=1, axes=(0, 1), name=None):
    return jnp.rot90(x, k=k, axes=tuple(axes))


def roll(x, shifts, axis=None, name=None):
    return jnp.roll(x, shifts, axis=axis)


def gather(x, index, axis=0, name=None):
    """Parity: paddle.gather (ref: operators/gather_op.cc) — select rows."""
    return jnp.take(jnp.asarray(x), jnp.asarray(index).astype(jnp.int32), axis=axis)


def gather_nd(x, index, name=None):
    """Parity: paddle.gather_nd (ref: operators/gather_nd_op.cc)."""
    x = jnp.asarray(x)
    index = jnp.asarray(index)
    return x[tuple(jnp.moveaxis(index, -1, 0))]


def scatter(x, index, updates, overwrite=True, name=None):
    """Parity: paddle.scatter (ref: operators/scatter_op.cc) — row scatter."""
    x = jnp.asarray(x)
    index = jnp.asarray(index).astype(jnp.int32).reshape(-1)
    updates = jnp.asarray(updates, x.dtype)
    if overwrite:
        return x.at[index].set(updates)
    # paddle semantics: non-overwrite zeroes target rows then accumulates
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter_nd_add(x, index, updates, name=None):
    x = jnp.asarray(x)
    index = jnp.asarray(index)
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(jnp.asarray(updates, x.dtype))


def scatter_nd(index, updates, shape, name=None):
    zeros = jnp.zeros(tuple(shape), dtype=jnp.asarray(updates).dtype)
    return scatter_nd_add(zeros, index, updates)


def index_select(x, index, axis=0, name=None):
    return jnp.take(jnp.asarray(x), jnp.asarray(index).astype(jnp.int32), axis=axis)


def index_sample(x, index, name=None):
    """Parity: paddle.index_sample — per-row gather (ref: operators/index_sample_op.cc)."""
    x = jnp.asarray(x)
    index = jnp.asarray(index).astype(jnp.int32)
    return jnp.take_along_axis(x, index, axis=1)


def index_add(x, index, axis, value, name=None):
    x = jnp.asarray(x)
    index = jnp.asarray(index).astype(jnp.int32)
    x_moved = jnp.moveaxis(x, axis, 0)
    v_moved = jnp.moveaxis(jnp.asarray(value, x.dtype), axis, 0)
    out = x_moved.at[index].add(v_moved)
    return jnp.moveaxis(out, 0, axis)


def index_put(x, indices, value, accumulate=False, name=None):
    x = jnp.asarray(x)
    idx = tuple(jnp.asarray(i) for i in indices)
    if accumulate:
        return x.at[idx].add(jnp.asarray(value, x.dtype))
    return x.at[idx].set(jnp.asarray(value, x.dtype))


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    arr = jnp.asarray(arr)
    indices = jnp.asarray(indices).astype(jnp.int32)
    values = jnp.broadcast_to(jnp.asarray(values, arr.dtype), indices.shape)
    dims = list(range(arr.ndim))
    idx = tuple(
        indices if d == axis else jax.lax.broadcasted_iota(jnp.int32, indices.shape, d)
        for d in dims
    )
    if reduce == "assign":
        return arr.at[idx].set(values)
    if reduce == "add":
        return arr.at[idx].add(values)
    if reduce in ("mul", "multiply"):
        return arr.at[idx].multiply(values)
    raise InvalidArgumentError(f"unknown reduce {reduce!r}")


def take_along_axis(arr, indices, axis, name=None):
    return jnp.take_along_axis(jnp.asarray(arr), jnp.asarray(indices).astype(jnp.int32), axis=axis)


def slice(input, axes, starts, ends, name=None):
    """Parity: paddle.slice (ref: operators/slice_op.cc)."""
    x = jnp.asarray(input)
    slices = [builtins.slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        slices[ax] = builtins.slice(int(st), int(en))
    return x[tuple(slices)]


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = jnp.asarray(x)
    slices = [builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        slices[ax] = builtins.slice(int(st), int(en), int(sd))
    return x[tuple(slices)]


def crop(x, shape=None, offsets=None, name=None):
    x = jnp.asarray(x)
    shape = list(shape) if shape is not None else list(x.shape)
    offsets = list(offsets) if offsets is not None else [0] * x.ndim
    shape = [x.shape[i] - offsets[i] if s == -1 else s for i, s in enumerate(shape)]
    return jax.lax.dynamic_slice(x, offsets, shape)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """Parity: paddle.nn.functional.pad / paddle.pad (ref: operators/pad_op.cc).

    ``pad`` is either a flat list covering all dims (paddle's pad2d style:
    last-dim-first pairs) or len(2*ndim) covering every dim.
    """
    x = jnp.asarray(x)
    pad = list(pad)
    nd = x.ndim
    if len(pad) == 2 * nd:
        # full spec, paddle order = [dim0_lo, dim0_hi, dim1_lo, ...]? The
        # reference uses per-dim pairs in dim order for paddle.pad.
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial spec applies to trailing spatial dims, last dim first
        npairs = len(pad) // 2
        widths = [(0, 0)] * nd
        for i in range(npairs):
            dim = nd - 1 - i
            widths[dim] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, widths, mode="constant", constant_values=value)
    return jnp.pad(x, widths, mode=jmode)


def cast(x, dtype, name=None):
    return jnp.asarray(x).astype(_dt.convert_dtype(dtype))


def repeat_interleave(x, repeats, axis=None, name=None):
    return jnp.repeat(x, repeats, axis=axis)


def unbind(input, axis=0, name=None):
    return unstack(input, axis=axis)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, name=None):
    """NOTE: output size is data-dependent — not jittable; eager/host-side only,
    same as the reference's unique op which runs on CPU for index outputs."""
    import numpy as np

    xs = np.asarray(x)
    res = np.unique(xs, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return jnp.asarray(res)
    return tuple(jnp.asarray(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    import numpy as np

    xs = np.asarray(x)
    if axis is None:
        xs = xs.ravel()
        axis = 0
    changed = np.ones(xs.shape[axis], dtype=bool)
    if xs.shape[axis] > 1:
        sl = np.any(
            np.take(xs, range(1, xs.shape[axis]), axis=axis)
            != np.take(xs, range(0, xs.shape[axis] - 1), axis=axis),
            axis=tuple(i for i in range(xs.ndim) if i != axis),
        ) if xs.ndim > 1 else (
            np.take(xs, range(1, xs.shape[axis])) != np.take(xs, range(0, xs.shape[axis] - 1))
        )
        changed[1:] = sl
    idx = np.nonzero(changed)[0]
    out = np.take(xs, idx, axis=axis)
    rets = [jnp.asarray(out)]
    if return_inverse:
        inv = np.cumsum(changed) - 1
        rets.append(jnp.asarray(inv))
    if return_counts:
        counts = np.diff(np.append(idx, xs.shape[axis]))
        rets.append(jnp.asarray(counts))
    return rets[0] if len(rets) == 1 else tuple(rets)


def masked_select(x, mask, name=None):
    """Data-dependent output size — eager/host-side only."""
    import numpy as np

    return jnp.asarray(np.asarray(x)[np.asarray(mask)])


def masked_fill(x, mask, value, name=None):
    x = jnp.asarray(x)
    return jnp.where(jnp.asarray(mask, bool), jnp.asarray(value, x.dtype), x)


def as_complex(x, name=None):
    x = jnp.asarray(x)
    return jax.lax.complex(x[..., 0], x[..., 1])


def as_real(x, name=None):
    x = jnp.asarray(x)
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def tensordot(x, y, axes=2, name=None):
    return jnp.tensordot(x, y, axes=axes)


def atleast_1d(*inputs, name=None):
    out = jnp.atleast_1d(*inputs)
    return out if isinstance(out, list) else out


def atleast_2d(*inputs, name=None):
    return jnp.atleast_2d(*inputs)


def atleast_3d(*inputs, name=None):
    return jnp.atleast_3d(*inputs)


def tolist(x):
    import numpy as np

    return np.asarray(x).tolist()


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Parity: paddle.shard_index (ref: operators/shard_index_op.cc) — used by
    sharded embedding tables (model-parallel lookup)."""
    input = jnp.asarray(input)
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (input // shard_size) == shard_id
    return jnp.where(in_shard, input % shard_size, ignore_value)

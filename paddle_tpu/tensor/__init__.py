"""paddle_tpu.tensor — the tensor function library.

A paddle_tpu Tensor IS a ``jax.Array``; this package provides the
paddle-2.0-parity free functions over it (reference surface:
python/paddle/tensor/).  There is no OpKernel registry: each function maps to
one or a few XLA HLO ops, and the XLA compiler does kernel selection, fusion,
layout and memory planning (replacing the reference's
framework/operator.h kernel dispatch + framework/ir/ passes).
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .attribute import *  # noqa: F401,F403
from .to_string import *  # noqa: F401,F403

from . import creation, math, manipulation, linalg, logic, random, search, stat, attribute  # noqa: F401

# stat exports under distinct names to avoid clobbering math.mean (identical behavior)
from .stat import std, var, quantile, nanquantile, histogramdd  # noqa: F401

"""Search / sort / top-k ops.

Parity surface: python/paddle/tensor/search.py (reference ops:
operators/top_k_op.cc, arg_max/arg_min, argsort, where, nonzero).
top_k lowers to XLA's sort/partial-sort which is TPU-tuned.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "where", "nonzero",
    "index_select_search", "kthvalue", "mode", "median", "nanmedian",
    "searchsorted", "bucketize", "masked_select_idx",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..framework import dtype as _dt

    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(_dt.convert_dtype(dtype))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..framework import dtype as _dt

    out = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(_dt.convert_dtype(dtype))


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    idx = jnp.argsort(x, axis=axis, stable=True, descending=descending)
    return idx.astype(jnp.int64)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    out = jnp.sort(x, axis=axis, stable=True, descending=descending)
    return out


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    """Parity: paddle.topk (ref: operators/top_k_v2_op.cc)."""
    x = jnp.asarray(x)
    if axis is None:
        axis = -1
    x_moved = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(x_moved, k)
    else:
        vals, idx = jax.lax.top_k(-x_moved, k)
        vals = -vals
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx.astype(jnp.int64), -1, axis)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return jnp.where(condition, x, y)


def nonzero(x, as_tuple=False):
    """Data-dependent output shape — host-side eager only (as in the
    reference, where-index op runs with dynamic output)."""
    import numpy as np

    idx = np.nonzero(np.asarray(x))
    if as_tuple:
        return tuple(jnp.asarray(i.reshape(-1, 1)) for i in idx)
    return jnp.asarray(np.stack(idx, axis=1).astype(np.int64))


def index_select_search(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = jnp.asarray(x)
    sorted_vals = jnp.sort(x, axis=axis)
    sorted_idx = jnp.argsort(x, axis=axis)
    vals = jnp.take(sorted_vals, k - 1, axis=axis)
    idx = jnp.take(sorted_idx, k - 1, axis=axis).astype(jnp.int64)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx


def mode(x, axis=-1, keepdim=False, name=None):
    x = jnp.asarray(x)
    sorted_x = jnp.sort(x, axis=axis)
    n = x.shape[axis]

    def count_runs(row):
        eq = jnp.concatenate([jnp.ones(1, bool), row[1:] == row[:-1]])
        run_id = jnp.cumsum(~eq)
        counts = jax.nn.one_hot(run_id, n, dtype=jnp.int32).sum(0)
        best_run = jnp.argmax(counts)
        pos = jnp.argmax(run_id == best_run)
        return row[pos], pos

    moved = jnp.moveaxis(sorted_x, axis, -1)
    flat = moved.reshape(-1, n)
    vals, pos = jax.vmap(count_runs)(flat)
    vals = vals.reshape(moved.shape[:-1])
    # paddle returns index into the *original* tensor of the last occurrence;
    # we return index into sorted order's first occurrence position mapped back
    sorted_idx = jnp.moveaxis(jnp.argsort(x, axis=axis), axis, -1).reshape(-1, n)
    orig_idx = jnp.take_along_axis(sorted_idx, pos[:, None], axis=1)[:, 0]
    idx = orig_idx.reshape(moved.shape[:-1]).astype(jnp.int64)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    x = jnp.asarray(x)
    from ..framework import dtype as _dt

    if not jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(_dt.get_default_dtype())
    if mode == "avg":
        return jnp.median(x, axis=axis, keepdims=keepdim)
    # mode == 'min': lower median
    if axis is None:
        flat = x.ravel()
        k = (flat.shape[0] - 1) // 2
        return jnp.sort(flat)[k]
    n = x.shape[axis]
    k = (n - 1) // 2
    out = jnp.take(jnp.sort(x, axis=axis), k, axis=axis)
    return jnp.expand_dims(out, axis) if keepdim else out


def nanmedian(x, axis=None, keepdim=False, name=None):
    return jnp.nanmedian(jnp.asarray(x), axis=axis, keepdims=keepdim)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    out = jnp.searchsorted(jnp.asarray(sorted_sequence), jnp.asarray(values),
                           side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def masked_select_idx(x, mask):
    import numpy as np

    return jnp.asarray(np.asarray(x)[np.asarray(mask)])

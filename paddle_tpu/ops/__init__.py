"""paddle_tpu.ops — Pallas TPU kernels for ops XLA won't fuse optimally.

The reference's 650-kernel operator library (paddle/fluid/operators/) maps
almost entirely to XLA-fused lax ops; this package holds the few hand
kernels that beat the compiler (flash attention; more as profiling finds
them).
"""
from .flash_attention import flash_attention  # noqa: F401

"""paddle_tpu.ops — Pallas TPU kernels for ops XLA won't fuse optimally.

The reference's 650-kernel operator library (paddle/fluid/operators/) maps
almost entirely to XLA-fused lax ops; this package holds the hand kernels
that beat the compiler, plus the autotuner that picks their tile sizes:

* ``flash_attention`` (+ ``flash_attention_fwd_lse`` /
  ``flash_attention_bwd_chunk``) — O(S)-memory attention, forward and
  backward, triangle-grid causal path (flash_attention.py);
* ``conv1x1_bn_relu`` / ``conv1x1_bn_stats`` / ``bn_apply_relu`` —
  1x1-conv GEMM with the train-mode BatchNorm statistics fused into the
  epilogue, plus the one-pass normalize + residual-add + ReLU apply
  kernel the ResNet bottleneck tail dispatches to
  (fused_conv1x1_bn.py);
* ``grouped_matmul`` — one masked matmul over the MoE experts' ragged
  capacity-bucketed row groups (grouped_matmul.py);
* ``paged_flash_decode`` — flash-decode attention over a paged KV pool
  with the page-table walk in-kernel and per-page int8/fp8 dequant
  fused into the online-softmax loop, the paged serving decode hot
  path (paged_attention.py);
* ``quantized_matmul`` / ``fp8_matmul`` — int8×int8→int32 (and
  fp8-e4m3) matmul with the dequant + bias epilogue fused, the serving
  quantization hot path (quantized_matmul.py);
* ``layernorm_residual`` — residual add + LayerNorm in one HBM pass
  (fused_layernorm.py);
* ``softmax_cross_entropy`` — online-logsumexp label cross-entropy that
  never materializes the [rows, vocab] probability matrix
  (fused_softmax_xent.py);
* ``autotune`` — measured block-size search with a persistent on-disk
  cache; every kernel above resolves its tile parameters through it
  (autotune.py).
"""
from . import autotune  # noqa: F401
from .flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_bwd_chunk,
    flash_attention_fwd_lse,
)
from .fused_conv1x1_bn import (  # noqa: F401
    bn_apply_relu,
    conv1x1_bn_relu,
    conv1x1_bn_stats,
)
from .fused_layernorm import layernorm_residual  # noqa: F401
from .grouped_matmul import grouped_matmul  # noqa: F401
from .paged_attention import (  # noqa: F401
    paged_flash_decode,
    paged_flash_eligible,
)
from .quantized_matmul import (  # noqa: F401
    fp8_matmul,
    quantized_linear,
    quantized_matmul,
)
from .fused_softmax_xent import softmax_cross_entropy  # noqa: F401

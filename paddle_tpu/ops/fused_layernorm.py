"""Fused LayerNorm + residual-add epilogue — Pallas TPU kernel.

Every transformer sublayer boundary runs ``s = x + sublayer_out`` followed
by (or preceded by, post-LN) ``LayerNorm(s)``.  Under XLA these are
separate HBM passes when the LN's reduction breaks fusion with the big
matmul producing ``sublayer_out``: write s, read s for mean/var, read s
again to normalize.  This kernel streams row blocks once — the residual
add, both statistics and the normalize+affine all happen on the block
while it sits in VMEM:

    XLA:    s = x + r        write s            (pass 1)
            mean/var over s  read s             (pass 2)
            normalize+affine read s, write y    (pass 3)
    here:   s, y = layernorm_residual(x, r, g, b)   read x,r / write s,y

Both the residual stream ``s`` and the normalized ``y`` are returned —
pre-LN blocks (GPT) consume both (``s`` carries forward, ``y`` feeds the
next sublayer), post-LN blocks (BERT) consume ``y``.  The backward is the
standard closed-form LayerNorm VJP in plain XLA (three row reductions that
fuse into one pass — no second custom kernel needed).

Numerics match ``nn.functional.layer_norm`` exactly: the sum is rounded
to the activation dtype first (that rounded value is what the unfused
path normalizes), statistics accumulate in float32.

Tile sizes come from ``ops.autotune`` (kernel name "layernorm_residual");
the feature dim stays whole per block, so eligibility on real TPUs wants
``D % 128 == 0`` (``autotune.fused_epilogues_eligible``).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # jax < 0.6 spells it TPUCompilerParams
    pltpu.CompilerParams = pltpu.TPUCompilerParams

from ..framework.errors import InvalidArgumentError
from . import autotune as _at

__all__ = ["layernorm_residual"]


def _kernel(x_ref, r_ref, g_ref, b_ref, s_ref, y_ref, mean_ref, rstd_ref,
            *, epsilon: float):
    s32 = x_ref[...].astype(jnp.float32) + r_ref[...].astype(jnp.float32)
    s_out = s32.astype(s_ref.dtype)
    s_ref[...] = s_out
    # normalize the ROUNDED sum — that is what the unfused path sees
    sf = s_out.astype(jnp.float32)
    mean = jnp.mean(sf, axis=-1, keepdims=True)
    c = sf - mean
    var = jnp.mean(c * c, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + epsilon)
    y = c * rstd
    y = y * g_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _ln_res_pallas(x, r, g, b, epsilon, block_m):
    """2-D [M, D] impl; returns (s, y, mean, rstd) with stats [M, 1] f32."""
    M, D = x.shape
    bm = min(block_m, max(M, 8))
    bm = -(-bm // 8) * 8
    Mp = -(-M // bm) * bm
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))
        r = jnp.pad(r, ((0, Mp - M), (0, 0)))
    g2 = g.reshape(1, D)
    b2 = b.reshape(1, D)

    interpret = jax.default_backend() != "tpu"
    row = lambda i: (i, 0)  # noqa: E731
    s, y, mean, rstd = pl.pallas_call(
        functools.partial(_kernel, epsilon=epsilon),
        interpret=interpret,
        grid=(Mp // bm,),
        in_specs=[
            pl.BlockSpec((bm, D), row),
            pl.BlockSpec((bm, D), row),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, D), row),
            pl.BlockSpec((bm, D), row),
            pl.BlockSpec((bm, 1), row),
            pl.BlockSpec((bm, 1), row),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Mp, D), x.dtype),
            jax.ShapeDtypeStruct((Mp, D), x.dtype),
            jax.ShapeDtypeStruct((Mp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Mp, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(x, r, g2, b2)
    return s[:M], y[:M], mean[:M], rstd[:M]


def _space(x, r, g, b, **_):
    M, D = x.shape
    itemsize = np.dtype(x.dtype).itemsize
    out = []
    for bm in _at.tile_candidates(M, base=(128, 256, 512, 1024, 2048)):
        # resident: x/r in + s/y out blocks, f32 compute copy, affine rows
        resident = 4 * bm * D * itemsize + bm * D * 4 + 2 * D * 4
        if _at.vmem_fits(resident):
            out.append({"block_m": bm})
    return out


@_at.autotune("layernorm_residual", params=("block_m",), space=_space,
              heuristic=lambda *a, **k: {"block_m": 512})
def _ln_res_measured(x, r, g, b, *, epsilon, block_m):
    return _ln_res_pallas(x, r, g, b, epsilon, block_m)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _ln_res(x, r, g, b, epsilon, block_m):
    s, y, _, _ = _ln_res_pallas(x, r, g, b, epsilon, block_m)
    return s, y


def _ln_res_fwd(x, r, g, b, epsilon, block_m):
    s, y, mean, rstd = _ln_res_pallas(x, r, g, b, epsilon, block_m)
    return (s, y), (s, mean, rstd, g)


def _ln_res_bwd(epsilon, block_m, res, cts):
    s, mean, rstd, g = res
    ds_out, dy = cts
    sf = s.astype(jnp.float32)
    xhat = (sf - mean) * rstd
    dxhat = dy.astype(jnp.float32) * g.astype(jnp.float32)
    # closed-form LayerNorm VJP — three row reductions XLA fuses into one
    # pass over the block
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    ds = ds_out.astype(jnp.float32) + rstd * (dxhat - m1 - xhat * m2)
    dyf = dy.astype(jnp.float32)
    dg = jnp.sum(dyf * xhat, axis=0)
    db = jnp.sum(dyf, axis=0)
    return (ds.astype(s.dtype), ds.astype(s.dtype),
            dg.astype(g.dtype), db.astype(g.dtype))


_ln_res.defvjp(_ln_res_fwd, _ln_res_bwd)


def layernorm_residual(x, residual, weight, bias, *, epsilon: float = 1e-5,
                       block_m: Optional[int] = None):
    """``s = x + residual;  y = LayerNorm(s) * weight + bias`` in one pass.

    x/residual: ``[..., D]`` (same shape/dtype), weight/bias: ``[D]``.
    Returns ``(s, y)`` — the residual stream and the normalized output;
    pre-LN blocks use both, post-LN blocks use ``y``.  Differentiable in
    x, residual, weight and bias.  ``block_m`` defaults to the autotuner.
    """
    x = jnp.asarray(x)
    residual = jnp.asarray(residual)
    weight = jnp.asarray(weight)
    bias = jnp.asarray(bias)
    if x.shape != residual.shape:
        raise InvalidArgumentError(
            f"layernorm_residual: x {x.shape} vs residual {residual.shape}")
    D = x.shape[-1]
    if weight.shape != (D,) or bias.shape != (D,):
        raise InvalidArgumentError(
            f"layernorm_residual: affine shapes {weight.shape}/{bias.shape} "
            f"do not match feature dim {D}")
    lead = x.shape[:-1]
    x2 = x.reshape(-1, D)
    r2 = residual.reshape(-1, D)
    if block_m is None:
        cfg = _ln_res_measured.config(x2, r2, weight, bias,
                                      epsilon=float(epsilon))
        block_m = cfg["block_m"]
    s, y = _ln_res(x2, r2, weight, bias, float(epsilon), int(block_m))
    return s.reshape(*lead, D), y.reshape(*lead, D)

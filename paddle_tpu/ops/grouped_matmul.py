"""Grouped matmul over ragged per-expert row groups — Pallas TPU kernel.

The MoE expert FFN (paddle_tpu/moe) is E independent matmuls whose row
counts are decided at runtime by the router: expert ``e`` owns the first
``group_sizes[e]`` rows of its ``[C, D]`` capacity bucket and the rest is
padding.  Under XLA the natural spelling is a batched einsum over the
full ``[E, C, D]`` buffer — every padding row burns MXU cycles and HBM
bandwidth.  This kernel runs one matmul per (expert, row-block,
col-block) grid step and masks the padding rows in-register, so the
output is exactly the masked einsum while each block stays in VMEM:

    XLA:    y = einsum("ecd,edf->ecf", x * rowmask, w)   (mask in HBM)
    here:   y = grouped_matmul(x, w, group_sizes)        (mask in VMEM)

Rows at or beyond ``group_sizes[e]`` are exactly zero in the output, so
downstream combine sums can trust the padding without re-masking.  The
backward is the closed-form VJP in plain XLA (two masked einsums — they
batch over E and fuse fine; no second custom kernel needed):

    dx = einsum("ecf,edf->ecd", dy, w) * rowmask
    dw = einsum("ecd,ecf->edf", x * rowmask, dy)

``group_sizes`` gets a symbolic-zero (float0) cotangent.

Tile sizes come from ``ops.autotune`` (kernel name "grouped_matmul");
the contraction dim D stays whole per block, so eligibility on real
TPUs wants ``D % 128 == 0`` (same shape class as the other epilogues).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # jax < 0.6 spells it TPUCompilerParams
    pltpu.CompilerParams = pltpu.TPUCompilerParams

from ..framework.errors import InvalidArgumentError
from . import autotune as _at

__all__ = ["grouped_matmul"]


def _kernel(gs_ref, x_ref, w_ref, o_ref):
    i = pl.program_id(1)
    bm, bn = o_ref.shape[1], o_ref.shape[2]
    gs = gs_ref[0, 0]
    acc = jnp.dot(x_ref[0].astype(jnp.float32),
                  w_ref[0].astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    # global row ids of this block; rows past the group's fill are padding
    rows = jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 0) + i * bm
    acc = jnp.where(rows < gs, acc, 0.0)
    o_ref[0] = acc.astype(o_ref.dtype)


def _gmm_pallas(x, w, group_sizes, block_m, block_n):
    """[E, C, D] @ [E, D, F] with per-expert valid-row counts [E] i32."""
    E, C, D = x.shape
    F = w.shape[2]
    bm = min(block_m, max(C, 8))
    bm = -(-bm // 8) * 8
    bn = min(block_n, max(F, 128))
    bn = -(-bn // 128) * 128
    Cp = -(-C // bm) * bm
    Fp = -(-F // bn) * bn
    if Cp != C:
        x = jnp.pad(x, ((0, 0), (0, Cp - C), (0, 0)))
    if Fp != F:
        w = jnp.pad(w, ((0, 0), (0, 0), (0, Fp - F)))
    gs2 = group_sizes.reshape(E, 1).astype(jnp.int32)

    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        _kernel,
        interpret=interpret,
        grid=(E, Cp // bm, Fp // bn),
        in_specs=[
            pl.BlockSpec((1, 1), lambda e, i, j: (e, 0)),
            pl.BlockSpec((1, bm, D), lambda e, i, j: (e, i, 0)),
            pl.BlockSpec((1, D, bn), lambda e, i, j: (e, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, bm, bn), lambda e, i, j: (e, i, j)),
        out_shape=jax.ShapeDtypeStruct((E, Cp, Fp), x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
    )(gs2, x, w)
    return out[:, :C, :F]


def _space(x, w, group_sizes, **_):
    E, C, D = x.shape
    F = w.shape[2]
    itemsize = np.dtype(x.dtype).itemsize
    out = []
    for bm in _at.tile_candidates(C, base=(64, 128, 256, 512)):
        for bn in _at.tile_candidates(F, multiple=_at.LANE,
                                      base=(128, 256, 512)):
            # resident: x row block, w col block, f32 acc + out block
            resident = (bm * D + D * bn) * itemsize + bm * bn * (4 + itemsize)
            if _at.vmem_fits(resident):
                out.append({"block_m": bm, "block_n": bn})
    return out


@_at.autotune("grouped_matmul", params=("block_m", "block_n"), space=_space,
              heuristic=lambda *a, **k: {"block_m": 128, "block_n": 128})
def _gmm_measured(x, w, group_sizes, *, block_m, block_n):
    return _gmm_pallas(x, w, group_sizes, block_m, block_n)


def _rowmask(group_sizes, C):
    # [E, C, 1] — 1.0 for valid rows, 0.0 for capacity padding
    rows = jnp.arange(C, dtype=jnp.int32)[None, :]
    return (rows < group_sizes[:, None]).astype(jnp.float32)[..., None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _gmm(x, w, group_sizes, block_m, block_n):
    return _gmm_pallas(x, w, group_sizes, block_m, block_n)


def _gmm_fwd(x, w, group_sizes, block_m, block_n):
    y = _gmm_pallas(x, w, group_sizes, block_m, block_n)
    return y, (x, w, group_sizes)


def _gmm_bwd(block_m, block_n, res, dy):
    x, w, group_sizes = res
    mask = _rowmask(group_sizes, x.shape[1]).astype(dy.dtype)
    dx = jnp.einsum("ecf,edf->ecd", dy, w) * mask
    dw = jnp.einsum("ecd,ecf->edf", x * mask.astype(x.dtype), dy)
    return (dx.astype(x.dtype), dw.astype(w.dtype),
            np.zeros(group_sizes.shape, jax.dtypes.float0))


_gmm.defvjp(_gmm_fwd, _gmm_bwd)


def grouped_matmul(x, w, group_sizes, *, block_m: Optional[int] = None,
                   block_n: Optional[int] = None):
    """Per-expert matmul over ragged row groups in one kernel launch.

    x: ``[E, C, D]`` capacity-bucketed rows (expert-major), w: ``[E, D,
    F]`` stacked expert weights, group_sizes: ``[E]`` integer valid-row
    counts.  Returns ``[E, C, F]`` equal to ``einsum("ecd,edf->ecf", x *
    rowmask, w)`` — rows at or beyond ``group_sizes[e]`` are exactly
    zero.  Differentiable in x and w; ``group_sizes`` gets a
    symbolic-zero cotangent.  Blocks default to the autotuner.
    """
    x = jnp.asarray(x)
    w = jnp.asarray(w)
    group_sizes = jnp.asarray(group_sizes)
    if x.ndim != 3 or w.ndim != 3:
        raise InvalidArgumentError(
            f"grouped_matmul: x {x.shape} / w {w.shape} must be rank 3")
    E, C, D = x.shape
    if w.shape[0] != E or w.shape[1] != D:
        raise InvalidArgumentError(
            f"grouped_matmul: w {w.shape} does not match x {x.shape} "
            f"(want [E={E}, D={D}, F])")
    if group_sizes.shape != (E,):
        raise InvalidArgumentError(
            f"grouped_matmul: group_sizes {group_sizes.shape} != ({E},)")
    if not jnp.issubdtype(group_sizes.dtype, jnp.integer):
        raise InvalidArgumentError(
            f"grouped_matmul: group_sizes dtype {group_sizes.dtype} is "
            f"not integer")
    group_sizes = group_sizes.astype(jnp.int32)
    if block_m is None or block_n is None:
        cfg = _gmm_measured.config(x, w, group_sizes)
        block_m = cfg["block_m"] if block_m is None else block_m
        block_n = cfg["block_n"] if block_n is None else block_n
    return _gmm(x, w, group_sizes, int(block_m), int(block_n))

"""Fused 1x1-conv (GEMM) + BatchNorm-statistics Pallas kernel.

ResNet-50's 1x1 convolutions carry ~55% of its FLOPs and are
HBM-bandwidth-bound on v5e (tools/resnet_mfu_analysis.md: arithmetic
intensity 32-128 flop/byte vs the chip's ~243 balance point), so the win
is not more FLOP/s but FEWER passes over the activation tensor.  Train-
mode BatchNorm needs the batch mean/var of the conv OUTPUT, which XLA
computes as a separate reduction pass over Y after the conv custom call:

    XLA:    Y = conv(x, w)      write Y          (pass 1)
            mean/var over Y     read Y           (pass 2)
            normalize+relu      read+write Y     (pass 3)

This kernel folds the statistics into the GEMM epilogue — per-channel
sum and sum-of-squares accumulate in VMEM scratch while the matmul tiles
stream through the MXU, finalized on the last M-step of the sequential
TPU grid:

    here:   Y, Σ, Σ² = conv1x1_bn_stats(x, w)    write Y  (pass 1)
            normalize+relu      read+write Y     (pass 2)

i.e. one full read of Y removed (~25-33% of the tensor traffic on these
bandwidth-bound layers).  The normalize pass stays in XLA where it fuses
with the residual add and ReLU for free.

Reference capability matched: the fused_ops family
(paddle/fluid/operators/fused/conv_fusion_op.cc — cuDNN conv+bias+act
fusion); the TPU-native answer fuses what the TPU is short on (HBM
passes), not what cuDNN is short on (kernel launches).

Layout: NHWC.  A 1x1/s1 conv is exactly ``X[M=N*H*W, K=Cin] @ W[K, N=Cout]``.
Grid: (N-blocks, M-blocks) with M minor — the TPU grid is sequential, so
the VMEM stats scratch accumulates across the M sweep of each N column
and flushes once per N-block.  K is kept whole (ResNet's Cin ≤ 2048
easily fits VMEM at bf16).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # jax < 0.6 spells it TPUCompilerParams
    pltpu.CompilerParams = pltpu.TPUCompilerParams

from ..framework.errors import InvalidArgumentError
from . import autotune as _at

__all__ = ["conv1x1_bn_stats", "conv1x1_bn_relu", "bn_apply_relu"]


def _kernel(x_ref, w_ref, y_ref, sum_ref, sq_ref, acc_s, acc_q):
    mi = pl.program_id(1)
    m_steps = pl.num_programs(1)

    x = x_ref[...]
    w = w_ref[...]
    y = jnp.dot(x, w, preferred_element_type=jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)

    @pl.when(mi == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)
        acc_q[...] = jnp.zeros_like(acc_q)

    # per-channel stats ride VMEM scratch across the sequential M sweep
    acc_s[...] += jnp.sum(y, axis=0, keepdims=True)
    acc_q[...] += jnp.sum(y * y, axis=0, keepdims=True)

    @pl.when(mi == m_steps - 1)
    def _flush():
        sum_ref[...] = acc_s[...]
        sq_ref[...] = acc_q[...]


def _space(x, w):
    """Candidate (block_m, block_n) tiles: Mosaic-aligned, clamped to the
    padded problem, filtered by the resident-VMEM estimate (x, w and y
    blocks plus the two stats scratch rows)."""
    M, K = x.shape
    N = w.shape[1]
    itemsize = np.dtype(x.dtype).itemsize
    out = []
    for bm in _at.tile_candidates(M, base=(128, 256, 512, 1024)):
        for bn in _at.tile_candidates(N, multiple=_at.LANE,
                                      base=(128, 256, 512)):
            resident = (bm * K + K * bn + bm * bn) * itemsize + 2 * bn * 4
            if _at.vmem_fits(resident):
                out.append({"block_m": bm, "block_n": bn})
    return out


def _heuristic(x, w):
    # the pre-autotuner defaults — the in-kernel clamp keeps them valid
    # (and bit-identical to the old behavior) at every shape
    return {"block_m": 512, "block_n": 256}


@_at.autotune("conv1x1_bn_stats", params=("block_m", "block_n"),
              space=_space, heuristic=_heuristic)
@functools.partial(jax.jit, static_argnames=("block_m", "block_n"))
def _conv1x1_bn_stats(x, w, *, block_m: int, block_n: int):
    M, K = x.shape
    K2, N = w.shape
    if K != K2:
        raise InvalidArgumentError(f"shape mismatch {x.shape} @ {w.shape}")
    # Mosaic lowers (sublane, lane)-tiled blocks: bm must be a multiple of
    # 8 and bn a multiple of 128, or non-aligned shapes (M=100, N=200)
    # fail to lower on a real TPU.  Padding already keeps the stats exact.
    bm = min(block_m, max(M, 8))
    bn = min(block_n, max(N, 128))
    bm = -(-bm // 8) * 8
    bn = -(-bn // 128) * 128
    Mp = -(-M // bm) * bm
    Np = -(-N // bn) * bn
    xp = x if Mp == M else jnp.pad(x, ((0, Mp - M), (0, 0)))
    wp = w if Np == N else jnp.pad(w, ((0, 0), (0, Np - N)))

    interpret = jax.default_backend() != "tpu"  # CPU tests: interpret mode
    y, s, q = pl.pallas_call(
        _kernel,
        interpret=interpret,
        grid=(Np // bn, Mp // bm),  # M minor: sequential stats sweep
        in_specs=[
            pl.BlockSpec((bm, K), lambda n, m: (m, 0)),
            pl.BlockSpec((K, bn), lambda n, m: (0, n)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda n, m: (m, n)),
            pl.BlockSpec((1, bn), lambda n, m: (0, n)),
            pl.BlockSpec((1, bn), lambda n, m: (0, n)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Mp, Np), x.dtype),
            jax.ShapeDtypeStruct((1, Np), jnp.float32),
            jax.ShapeDtypeStruct((1, Np), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, bn), jnp.float32),
            pltpu.VMEM((1, bn), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(xp, wp)
    return y[:M, :N], s[0, :N], q[0, :N]


def conv1x1_bn_stats(x, w, *, block_m: Optional[int] = None,
                     block_n: Optional[int] = None):
    """``Y = X @ W`` plus per-output-channel ``(Σy, Σy²)`` in ONE pass.

    x: ``[M, Cin]`` (flattened NHWC activations), w: ``[Cin, Cout]``.
    Returns ``(y [M, Cout], sum [Cout] f32, sumsq [Cout] f32)``.
    M and Cout are padded to block multiples internally (padding rows
    contribute zeros to the stats — exact).

    Tile sizes default to the autotuner (``ops.autotune``): measured on
    TPU, the 512x256 heuristic elsewhere.  Pass ``block_m``/``block_n``
    explicitly to bypass tuning.
    """
    return _conv1x1_bn_stats(x, w, block_m=block_m, block_n=block_n)


def _apply_kernel(*refs, has_residual):
    # normalize + (residual add) + relu on one (bm, bn) tile: Y and the
    # residual are each read once, the output written once.
    if has_residual:
        y_ref, sc_ref, sh_ref, r_ref, o_ref = refs
    else:
        y_ref, sc_ref, sh_ref, o_ref = refs
    out = y_ref[...].astype(jnp.float32) * sc_ref[...] + sh_ref[...]
    if has_residual:
        out = out + r_ref[...].astype(jnp.float32)
    o_ref[...] = jnp.maximum(out, 0.0).astype(o_ref.dtype)


def _apply_space(y, scale, shift, residual):
    M, N = y.shape
    itemsize = np.dtype(y.dtype).itemsize
    n_tiles = 3 if residual is not None else 2
    out = []
    for bm in _at.tile_candidates(M, base=(256, 512, 1024)):
        for bn in _at.tile_candidates(N, multiple=_at.LANE,
                                      base=(128, 256, 512)):
            resident = n_tiles * bm * bn * itemsize + 2 * bn * 4
            if _at.vmem_fits(resident):
                out.append({"block_m": bm, "block_n": bn})
    return out


def _apply_heuristic(y, scale, shift, residual):
    return {"block_m": 512, "block_n": 256}


@_at.autotune("conv1x1_bn_apply", params=("block_m", "block_n"),
              space=_apply_space, heuristic=_apply_heuristic)
@functools.partial(jax.jit, static_argnames=("block_m", "block_n"))
def _bn_apply(y, scale, shift, residual, *, block_m: int, block_n: int):
    M, N = y.shape
    bm = min(block_m, max(M, 8))
    bn = min(block_n, max(N, 128))
    bm = -(-bm // 8) * 8
    bn = -(-bn // 128) * 128
    Mp = -(-M // bm) * bm
    Np = -(-N // bn) * bn
    yp = y if (Mp, Np) == (M, N) else jnp.pad(y, ((0, Mp - M), (0, Np - N)))
    scp = scale.reshape(1, N).astype(jnp.float32)
    shp = shift.reshape(1, N).astype(jnp.float32)
    if Np != N:
        scp = jnp.pad(scp, ((0, 0), (0, Np - N)))
        shp = jnp.pad(shp, ((0, 0), (0, Np - N)))
    has_residual = residual is not None
    operands = [yp, scp, shp]
    in_specs = [
        pl.BlockSpec((bm, bn), lambda n, m: (m, n)),
        pl.BlockSpec((1, bn), lambda n, m: (0, n)),
        pl.BlockSpec((1, bn), lambda n, m: (0, n)),
    ]
    if has_residual:
        rp = residual if (Mp, Np) == (M, N) else jnp.pad(
            residual, ((0, Mp - M), (0, Np - N)))
        operands.append(rp)
        in_specs.append(pl.BlockSpec((bm, bn), lambda n, m: (m, n)))

    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        functools.partial(_apply_kernel, has_residual=has_residual),
        interpret=interpret,
        grid=(Np // bn, Mp // bm),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda n, m: (m, n)),
        out_shape=jax.ShapeDtypeStruct((Mp, Np), y.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
    )(*operands)
    return out[:M, :N]


def bn_apply_relu(y, scale, shift, residual=None, *,
                  block_m: Optional[int] = None,
                  block_n: Optional[int] = None):
    """Fused BN-normalize + residual-add + ReLU epilogue:
    ``relu(y*scale + shift [+ residual])`` in ONE pass over ``y``.

    The XLA tail of :func:`conv1x1_bn_relu` is elementwise, but it sits
    downstream of a Pallas custom call XLA cannot fuse INTO, so whether
    the normalize, the residual read and the ReLU land in one fusion is
    the compiler's choice.  This kernel pins them: one read of ``y``, one
    read of the residual, one write of the output — the guaranteed
    2-pass schedule of the module doc.  y ``[M, Cout]``, scale/shift
    ``[Cout]`` (f32 math), residual optional ``[M, Cout]``.
    """
    return _bn_apply(y, scale, shift, residual,
                     block_m=block_m, block_n=block_n)


def conv1x1_bn_relu(x, w, gamma, beta, *, epsilon: float = 1e-5,
                    residual=None, momentum: float = 0.9,
                    running_mean=None, running_var=None,
                    fused_epilogue: bool = False,
                    block_m: Optional[int] = None,
                    block_n: Optional[int] = None):
    """Train-mode ``relu(BN(X @ W) [+ residual])`` in two passes instead of
    XLA's three (see module doc).  x ``[M, Cin]`` NHWC-flattened.

    Returns ``(out [M, Cout], new_running_mean, new_running_var)`` with
    paddle's momentum convention (``new = momentum*old + (1-m)*batch``);
    running stats pass through unchanged when not provided.

    ``fused_epilogue=True`` routes the normalize + residual-add + ReLU
    tail through :func:`bn_apply_relu` (one pinned pass) instead of
    leaving the elementwise tail to XLA's fusion heuristics.
    """
    M = x.shape[0]
    y, s, q = conv1x1_bn_stats(x, w, block_m=block_m, block_n=block_n)
    mean = s / M
    var = jnp.maximum(q / M - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + epsilon)
    scale = (gamma.astype(jnp.float32) * inv).astype(y.dtype)
    shift = (beta.astype(jnp.float32)
             - mean * gamma.astype(jnp.float32) * inv).astype(y.dtype)
    if fused_epilogue:
        res = None if residual is None else residual.astype(y.dtype)
        out = bn_apply_relu(y, scale, shift, res)
    else:
        out = y * scale + shift
        if residual is not None:
            out = out + residual.astype(out.dtype)
        out = jax.nn.relu(out)
    if (running_mean is None) != (running_var is None):
        raise InvalidArgumentError(
            "conv1x1_bn_relu: pass running_mean and running_var together "
            "(or neither)")
    if running_mean is not None:
        n = jnp.asarray(M, jnp.float32)
        unbiased = var * n / jnp.maximum(n - 1, 1)
        running_mean = (momentum * running_mean.astype(jnp.float32)
                        + (1 - momentum) * mean)
        running_var = (momentum * running_var.astype(jnp.float32)
                       + (1 - momentum) * unbiased)
    return out, running_mean, running_var

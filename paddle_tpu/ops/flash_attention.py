"""Flash attention — Pallas TPU kernels, O(S) memory forward AND backward.

New capability (SURVEY §5: the reference has NO long-context support — no
flash/blockwise attention anywhere in the tree; its attention is the naive
matmul+softmax in python/paddle/nn/layer/transformer.py).

Design:
* All three kernels (fwd, dq, dk/dv) share one structure: a 3-D grid
  ``(batch·heads, owner-block, reduction-block)`` whose innermost dimension
  streams the *other* sequence through VMEM one block at a time, with the
  owner block's accumulators living in VMEM scratch across those steps.
  Nothing sequence-sized is ever resident: VMEM holds O(block²), HBM holds
  only the inputs/outputs — true O(S) memory at any length (validated at
  32k on v5e, where whole-sequence VMEM residency is impossible).
* **forward** keeps flash-2 online softmax (running max/sum, one rescale
  per block); saves per-row logsumexp, laid out ``[BH, S, 1]`` so stats
  load as native (block, 1) tiles — no 1-D→2-D vector reshapes, which
  Mosaic cannot legalize for some dtypes.
* **backward** is the flash-2 recurrence: ``delta = rowsum(dO·O)`` is one
  fused XLA elementwise-reduce; the dq kernel owns a q-block and streams
  kv; the dk/dv kernel owns a kv-block and streams q — each grid step owns
  its output tile outright, so there is no cross-step accumulation in HBM
  and no [B,H,S,block_k] score tile ever materializes.
* Causal self-attention takes a TRIANGLE grid: the flat grid enumerates
  only the causally-active tiles via scalar-prefetched (qi, ki) tables
  (the splash-attention pattern), so masked tiles skip their k/v DMA
  entirely, not just their compute — measured 139 ms → 100 ms for 32k
  causal fwd+bwd on v5e.  Non-square/offset cases keep the rectangular
  grid with ``pl.when`` compute predication; the q-position offset (ring
  attention) is taken in ELEMENTS, so any offset is exact.
* **ragged shapes pad-and-mask instead of falling back**: q/k/v pad up to
  block multiples and the kernels mask key positions ≥ the true kv length
  (-inf scores), so ANY shape takes the kernel path — the silent O(S²)
  fallback cliff is gone.
* On non-TPU backends the kernels run in Pallas interpret mode, so tests
  validate the exact kernel code path against the numpy oracle.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # jax < 0.6 spells it TPUCompilerParams
    pltpu.CompilerParams = pltpu.TPUCompilerParams

from . import autotune as _at

__all__ = ["flash_attention", "flash_attention_fwd_lse",
           "flash_attention_bwd_chunk"]

_NEG_INF = -jnp.inf


def _naive_reference(q, k, v, causal, sm_scale, q_offset=0):
    """[B,H,S,d] reference (tests only)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
    if causal:
        S, K = s.shape[-2], s.shape[-1]
        q_pos = q_offset + jnp.arange(S)
        mask = q_pos[:, None] >= jnp.arange(K)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    # fully-masked rows (ring chunks ahead of the diagonal) → zero output
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isneginf(s).all(-1, keepdims=True), 0.0, p)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _mask_scores(s, qi, ki, block_q, block_k, causal, q_offset, kv_len,
                 kv_seq):
    """kv-padding + causal masks for a [block_q, block_k] score tile.
    All index math pinned to i32: the package enables jax x64, which would
    otherwise promote Python ints to i64 and break Mosaic."""
    i32 = jnp.int32
    k_pos = ki * i32(block_k) + jax.lax.broadcasted_iota(i32, s.shape, 1)
    if kv_len < kv_seq:  # padded keys masked out
        s = jnp.where(k_pos < i32(kv_len), s, _NEG_INF)
    if causal:
        q_pos = i32(q_offset) + qi * i32(block_q) + \
            jax.lax.broadcasted_iota(i32, s.shape, 0)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return s


def _causal_run(qi, ki, block_q, block_k, q_offset, causal):
    """False iff the whole tile sits above the causal diagonal."""
    if not causal:
        return True
    i32 = jnp.int32
    last_q = i32(q_offset) + (qi + i32(1)) * i32(block_q) - i32(1)
    return ki * i32(block_k) <= last_q


# -- triangle grid: flat enumeration of ONLY the causally-active tiles ------
# With square blocks and q_offset == 0, row qi touches tiles ki ∈ [0, qi]
# (lower triangle, T = nq(nq+1)/2 tiles) and kv-row ki is touched by
# qi ∈ [ki, nq) (upper triangle).  Flattening the active set into the grid
# means masked tiles never exist as grid steps — their k/v DMA is skipped
# outright, not just their compute (the ~2x causal bandwidth win over the
# rectangular grid below, which must visit every tile and rely on
# _causal_run to skip compute).  The (qi, ki) per
# flat step comes from a host-precomputed i32 table delivered via scalar
# prefetch (PrefetchScalarGridSpec) — index maps stay table lookups, which
# Mosaic lowers directly (the splash-attention pattern); closed-form sqrt
# index math does not.
@functools.lru_cache(maxsize=64)
def _tri_lower_table(nq):
    """Two 1-D [T] arrays (qi, ki) enumerating the lower triangle
    row-major.  1-D because SMEM pads the trailing dim to the 128-lane
    tile — a [T, 2] table would waste 64x the scalar memory."""
    rows = [(qi, ki) for qi in range(nq) for ki in range(qi + 1)]
    a = np.asarray(rows, np.int32)
    return a[:, 0].copy(), a[:, 1].copy()


@functools.lru_cache(maxsize=64)
def _tri_upper_table(nq):
    """Two 1-D [T] arrays (ki, qi) enumerating the upper triangle by kv
    row."""
    rows = [(ki, qi) for ki in range(nq) for qi in range(ki, nq)]
    a = np.asarray(rows, np.int32)
    return a[:, 0].copy(), a[:, 1].copy()


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(*refs, kv_seq: int, kv_len: int, block_k: int, causal: bool,
                sm_scale: float, q_offset: int, triangle: bool = False):
    i32 = jnp.int32
    if triangle:  # flat grid over active tiles only (causal, square blocks):
        # (qi, ki) come from the scalar-prefetched table (leading ref)
        (qi_ref, ki_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
         acc_scr) = refs
        t = pl.program_id(1).astype(i32)
        qi, ki = qi_ref[t], ki_ref[t]
        first, last = ki == 0, ki == qi
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        qi = pl.program_id(1).astype(i32)
        ki = pl.program_id(2).astype(i32)
        first, last = ki == 0, ki == pl.num_programs(2) - 1
    block_q = q_ref.shape[1]

    @pl.when(first)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(triangle or _causal_run(qi, ki, block_q, block_k, q_offset,
                                     causal))
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        s = _mask_scores(s, qi, ki, block_q, block_k, causal, q_offset,
                         kv_len, kv_seq)
        m_prev = m_scr[:, :1]                      # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # guard fully-masked rows: exp(-inf − -inf) would be nan
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe)
        alpha = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_safe))
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(last)
    def _fin():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l == 0.0, _NEG_INF, m_scr[:, :1] + jnp.log(l_safe))
        lse_ref[0] = lse.astype(jnp.float32)


def _use_triangle(causal, q_offset, S, K, block_q, block_k):
    """The flat active-tile grid applies to the plain causal case: zero
    offset, square blocks, self-attention lengths."""
    return (causal and q_offset == 0 and S == K and block_q == block_k)


def _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k, q_offset,
                kv_len):
    B, H, S, D = q.shape
    K = k.shape[2]
    qs = q.reshape(B * H, S, D)
    ks = k.reshape(B * H, K, D)
    vs = v.reshape(B * H, K, D)

    _I0 = np.int32(0)  # index maps must stay i32 under global x64
    triangle = _use_triangle(causal, q_offset, S, K, block_q, block_k)

    kern = functools.partial(_fwd_kernel, kv_seq=K, kv_len=kv_len,
                             block_k=block_k, causal=causal,
                             sm_scale=sm_scale, q_offset=q_offset,
                             triangle=triangle)
    out_shape = [
        jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
    ]
    scratch = [
        pltpu.VMEM((block_q, 128), jnp.float32),  # running max
        pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
        pltpu.VMEM((block_q, D), jnp.float32),    # output accumulator
    ]
    interpret = jax.default_backend() != "tpu"

    if triangle:
        nq = S // block_q
        qi_t, ki_t = (jnp.asarray(a) for a in _tri_lower_table(nq))
        qmp = lambda b, t, qt, kt: (b, qt[t], _I0)  # noqa: E731
        kmp = lambda b, t, qt, kt: (b, kt[t], _I0)  # noqa: E731
        # grid (BH, T): the flat tile dim is innermost/sequential so the
        # owner block's VMEM accumulators persist across its tiles
        out, lse = pl.pallas_call(
            kern,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B * H, qi_t.shape[0]),
                in_specs=[
                    pl.BlockSpec((1, block_q, D), qmp),
                    pl.BlockSpec((1, block_k, D), kmp),
                    pl.BlockSpec((1, block_k, D), kmp),
                ],
                out_specs=[
                    pl.BlockSpec((1, block_q, D), qmp),
                    pl.BlockSpec((1, block_q, 1), qmp),
                ],
                scratch_shapes=scratch,
            ),
            out_shape=out_shape,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(qi_t, ki_t, qs, ks, vs)
        return out.reshape(B, H, S, D), lse.reshape(B, H, S)

    out, lse = pl.pallas_call(
        kern,
        grid=(B * H, S // block_q, K // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            # lse [BH, S, 1]: (block_q, 1) tiles — last dim full, no
            # 1-D vector reshapes anywhere
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, _I0)),
        ],
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qs, ks, vs)
    return out.reshape(B, H, S, D), lse.reshape(B, H, S)


# ---------------------------------------------------------------------------
# backward (flash-2 recurrence)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(*refs, kv_seq: int, kv_len: int, block_k: int,
                   causal: bool, sm_scale: float, q_offset: int,
                   triangle: bool = False):
    i32 = jnp.int32
    if triangle:
        (qi_ref, ki_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, acc_scr) = refs
        t = pl.program_id(1).astype(i32)
        qi, ki = qi_ref[t], ki_ref[t]
        first, last = ki == 0, ki == qi
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
         acc_scr) = refs
        qi = pl.program_id(1).astype(i32)
        ki = pl.program_id(2).astype(i32)
        first, last = ki == 0, ki == pl.num_programs(2) - 1
    block_q = q_ref.shape[1]

    @pl.when(first)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(triangle or _causal_run(qi, ki, block_q, block_k, q_offset,
                                     causal))
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        # fully-masked rows: lse = -inf AND every score -inf; replacing
        # lse with 0 makes p = exp(-inf − 0) = 0 with no bool broadcast
        lse = lse_ref[0]                           # (bq, 1)
        lse = jnp.where(jnp.isneginf(lse), 0.0, lse)
        delta = delta_ref[0]                       # (bq, 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        s = _mask_scores(s, qi, ki, block_q, block_k, causal, q_offset,
                         kv_len, kv_seq)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        acc_scr[...] = acc_scr[...] + jnp.dot(
            ds, k, preferred_element_type=jnp.float32)

    @pl.when(last)
    def _fin():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, block_q: int, causal: bool, sm_scale: float,
                    q_offset: int, kv_len: int, kv_seq: int,
                    triangle_nq: int = 0):
    i32 = jnp.int32
    if triangle_nq:  # flat upper-triangle grid: owner ki streams qi >= ki
        (ki_ref, qi_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        t = pl.program_id(1).astype(i32)
        ki, qi = ki_ref[t], qi_ref[t]
        first, last = qi == ki, qi == triangle_nq - 1
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
         dk_scr, dv_scr) = refs
        ki = pl.program_id(1).astype(i32)
        qi = pl.program_id(2).astype(i32)
        first, last = qi == 0, qi == pl.num_programs(2) - 1
    block_k = k_ref.shape[1]

    @pl.when(first)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(bool(triangle_nq) or _causal_run(qi, ki, block_q, block_k,
                                              q_offset, causal))
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                           # (bq, 1)
        lse = jnp.where(jnp.isneginf(lse), 0.0, lse)  # see dq kernel note
        delta = delta_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        s = _mask_scores(s, qi, ki, block_q, block_k, causal, q_offset,
                         kv_len, kv_seq)
        p = jnp.exp(s - lse)
        dv_scr[...] = dv_scr[...] + jnp.dot(
            p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[...] = dk_scr[...] + jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(last)
    def _fin():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_prepad(q, k, v, do, lse, delta, block_q, block_k):
    """Clamp this backward kernel's blocks to ITS OWN padded problem and
    pad every operand up to them — dq and dk/dv may run different tile
    sizes than the forward (the autotuner picks each independently), so
    each backward pallas_call re-establishes the block-multiple invariant
    itself.  New padded q rows carry do = 0, so their (garbage-lse)
    contributions to dq/dk/dv are exactly zero; padded kv columns are
    masked by kv_len as everywhere else."""
    S, K = q.shape[2], k.shape[2]
    bq, bk, padq, padk = _blocks_and_pad(S, K, block_q, block_k)
    return (bq, bk, padq(q), padk(k), padk(v), padq(do), padq(lse),
            padq(delta))


def _bwd_dq(q, k, v, do, lse, delta, causal, sm_scale, block_q, block_k,
            q_offset, kv_len):
    """dq half of the flash-2 backward: owns a q block, streams kv.
    lse/delta are [B, H, S] (unpadded trailing length is fine)."""
    S0 = q.shape[2]
    bq, bk, q, k, v, do, lse, delta = _bwd_prepad(q, k, v, do, lse, delta,
                                                  block_q, block_k)
    B, H, S, D = q.shape
    K = k.shape[2]
    qs = q.reshape(B * H, S, D)
    ks = k.reshape(B * H, K, D)
    vs = v.reshape(B * H, K, D)
    dos = do.reshape(B * H, S, D)
    lses = lse.reshape(B * H, S, 1)
    deltas = delta.reshape(B * H, S, 1)

    _I0 = np.int32(0)
    interpret = jax.default_backend() != "tpu"
    triangle = _use_triangle(causal, q_offset, S, K, bq, bk)
    block_q, block_k = bq, bk

    dq_kern = functools.partial(_bwd_dq_kernel, kv_seq=K, kv_len=kv_len,
                                block_k=block_k, causal=causal,
                                sm_scale=sm_scale, q_offset=q_offset,
                                triangle=triangle)
    dq_shape = jax.ShapeDtypeStruct((B * H, S, D), q.dtype)
    dq_scratch = [pltpu.VMEM((block_q, D), jnp.float32)]
    if triangle:
        nq = S // block_q
        qi_t, ki_t = (jnp.asarray(a) for a in _tri_lower_table(nq))
        qm = lambda b, t, qt, kt: (b, qt[t], _I0)  # noqa: E731
        km = lambda b, t, qt, kt: (b, kt[t], _I0)  # noqa: E731
        dq = pl.pallas_call(
            dq_kern,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B * H, qi_t.shape[0]),
                in_specs=[
                    pl.BlockSpec((1, block_q, D), qm),
                    pl.BlockSpec((1, block_k, D), km),
                    pl.BlockSpec((1, block_k, D), km),
                    pl.BlockSpec((1, block_q, D), qm),
                    pl.BlockSpec((1, block_q, 1), qm),
                    pl.BlockSpec((1, block_q, 1), qm),
                ],
                out_specs=pl.BlockSpec((1, block_q, D), qm),
                scratch_shapes=dq_scratch,
            ),
            out_shape=dq_shape,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(qi_t, ki_t, qs, ks, vs, dos, lses, deltas)
    else:
        dq = pl.pallas_call(
            dq_kern,
            grid=(B * H, S // block_q, K // block_k),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
                pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
                pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, _I0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, _I0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, D),
                                   lambda b, i, j: (b, i, _I0)),
            out_shape=dq_shape,
            scratch_shapes=dq_scratch,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=interpret,
        )(qs, ks, vs, dos, lses, deltas)
    return dq.reshape(B, H, S, D)[:, :, :S0]


def _bwd_dkv(q, k, v, do, lse, delta, causal, sm_scale, block_q, block_k,
             q_offset, kv_len):
    """dk/dv half of the flash-2 backward: owns a kv block, streams q."""
    K0 = k.shape[2]
    bq, bk, q, k, v, do, lse, delta = _bwd_prepad(q, k, v, do, lse, delta,
                                                  block_q, block_k)
    B, H, S, D = q.shape
    K = k.shape[2]
    qs = q.reshape(B * H, S, D)
    ks = k.reshape(B * H, K, D)
    vs = v.reshape(B * H, K, D)
    dos = do.reshape(B * H, S, D)
    lses = lse.reshape(B * H, S, 1)
    deltas = delta.reshape(B * H, S, 1)

    _I0 = np.int32(0)
    interpret = jax.default_backend() != "tpu"
    triangle = _use_triangle(causal, q_offset, S, K, bq, bk)
    block_q, block_k = bq, bk

    dkv_shape = [
        jax.ShapeDtypeStruct((B * H, K, D), k.dtype),
        jax.ShapeDtypeStruct((B * H, K, D), v.dtype),
    ]
    dkv_scratch = [
        pltpu.VMEM((block_k, D), jnp.float32),
        pltpu.VMEM((block_k, D), jnp.float32),
    ]
    if triangle:
        nq = S // block_q
        ki_u, qi_u = (jnp.asarray(a) for a in _tri_upper_table(nq))
        km = lambda b, t, kt, qt: (b, kt[t], _I0)  # noqa: E731
        qm = lambda b, t, kt, qt: (b, qt[t], _I0)  # noqa: E731
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, block_q=block_q,
                              causal=causal, sm_scale=sm_scale,
                              q_offset=q_offset, kv_len=kv_len, kv_seq=K,
                              triangle_nq=nq),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B * H, ki_u.shape[0]),
                in_specs=[
                    pl.BlockSpec((1, block_q, D), qm),
                    pl.BlockSpec((1, block_k, D), km),
                    pl.BlockSpec((1, block_k, D), km),
                    pl.BlockSpec((1, block_q, D), qm),
                    pl.BlockSpec((1, block_q, 1), qm),
                    pl.BlockSpec((1, block_q, 1), qm),
                ],
                out_specs=[
                    pl.BlockSpec((1, block_k, D), km),
                    pl.BlockSpec((1, block_k, D), km),
                ],
                scratch_shapes=dkv_scratch,
            ),
            out_shape=dkv_shape,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(ki_u, qi_u, qs, ks, vs, dos, lses, deltas)
    else:
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, block_q=block_q,
                              causal=causal, sm_scale=sm_scale,
                              q_offset=q_offset, kv_len=kv_len, kv_seq=K,
                              triangle_nq=0),
            grid=(B * H, K // block_k, S // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, _I0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
                pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, _I0)),
                pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, _I0)),
                pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, _I0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
            ],
            out_shape=dkv_shape,
            scratch_shapes=dkv_scratch,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=interpret,
        )(qs, ks, vs, dos, lses, deltas)

    return (dk.reshape(B, H, K, D)[:, :, :K0],
            dv.reshape(B, H, K, D)[:, :, :K0])


# ---------------------------------------------------------------------------
# autotuning (ops/autotune.py): the three kernels tune independently
# ---------------------------------------------------------------------------
def _seq_candidates(n):
    """Block candidates for a length-n sequence dim, clamped to the PADDED
    length — short serving buckets never pay full-width padded tiles."""
    return _at.tile_candidates(n, base=(128, 256, 512, 1024))


def _flash_space(q, k, v, *rest, causal=False, q_offset=0, **_):
    """Candidate (block_q, block_k) pairs.  The plain-causal case keeps
    square blocks only so every candidate stays on the triangle grid; the
    rectangular cases keep the aspect ratio within [1/2, 2] (strongly
    skewed tiles starve one of the matmul dims).  The VMEM estimate
    covers the resident q/k/v/do blocks, the f32 accumulators and the
    (block, 128) running-stat scratch."""
    S, K, D = q.shape[2], k.shape[2], q.shape[3]
    itemsize = np.dtype(q.dtype).itemsize
    square_only = causal and q_offset == 0 and S == K
    out = []
    for bq in _seq_candidates(S):
        for bk in _seq_candidates(K):
            if square_only:
                if bq != bk:
                    continue
            elif not 0.5 <= bq / bk <= 2.0:
                continue
            resident = ((2 * bq + 2 * bk) * D * itemsize
                        + (2 * bq * 128 + (bq + 2 * bk) * D + 2 * bq) * 4)
            if _at.vmem_fits(resident):
                out.append({"block_q": bq, "block_k": bk})
    return out


def _flash_heuristic(*args, **_):
    # the pre-autotuner defaults (512-blocks measured fastest on v5e at
    # 32k); _pick_block clamps them to short sequences exactly as before
    return {"block_q": 512, "block_k": 512}


_TUNE_KW = ("causal", "q_offset")  # non-array kwargs that shape the kernel


@_at.autotune("flash_fwd", params=("block_q", "block_k"),
              space=_flash_space, heuristic=_flash_heuristic,
              key_kwargs=_TUNE_KW)
def _fwd_tuned(q, k, v, *, causal, sm_scale, q_offset, kv_len,
               block_q, block_k):
    S, K = q.shape[2], k.shape[2]
    bq, bk, padq, padk = _blocks_and_pad(S, K, block_q, block_k)
    out, lse = _fwd_pallas(padq(q), padk(k), padk(v), causal, sm_scale,
                           bq, bk, q_offset, kv_len)
    return out[:, :, :S], lse[:, :, :S]


@_at.autotune("flash_bwd_dq", params=("block_q", "block_k"),
              space=_flash_space, heuristic=_flash_heuristic,
              key_kwargs=_TUNE_KW)
def _dq_tuned(q, k, v, do, lse, delta, *, causal, sm_scale, q_offset,
              kv_len, block_q, block_k):
    return _bwd_dq(q, k, v, do, lse, delta, causal, sm_scale, block_q,
                   block_k, q_offset, kv_len)


@_at.autotune("flash_bwd_dkv", params=("block_q", "block_k"),
              space=_flash_space, heuristic=_flash_heuristic,
              key_kwargs=_TUNE_KW)
def _dkv_tuned(q, k, v, do, lse, delta, *, causal, sm_scale, q_offset,
               kv_len, block_q, block_k):
    return _bwd_dkv(q, k, v, do, lse, delta, causal, sm_scale, block_q,
                    block_k, q_offset, kv_len)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, q_offset, kv_len,
           tuned):
    out, _ = _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k,
                         q_offset, kv_len)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, q_offset,
               kv_len, tuned):
    out, lse = _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k,
                           q_offset, kv_len)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, q_offset, kv_len, tuned,
               res, do):
    q, k, v, out, lse = res
    # delta = rowsum(dO ⊙ O): one fused elementwise+reduce in XLA,
    # loop-invariant across both backward kernels
    delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    # `tuned` (the forward took autotuner blocks): let each backward
    # kernel resolve its own tile sizes; explicit blocks pin both.
    bq, bk = (None, None) if tuned else (block_q, block_k)
    kw = dict(causal=causal, sm_scale=sm_scale, q_offset=q_offset,
              kv_len=kv_len, block_q=bq, block_k=bk)
    dq = _dq_tuned(q, k, v, do, lse, delta, **kw)
    dk, dv = _dkv_tuned(q, k, v, do, lse, delta, **kw)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def _round_up(x, m):
    return (x + m - 1) // m * m


def _pick_block(limit, n):
    """Largest block ≤ limit whose padding waste on a length-n sequence is
    ≤ max(n/8, 8) rows — e.g. S=600 takes 128-blocks (pad 40) rather than
    512-blocks (pad 424 = 70% wasted FLOPs)."""
    b = min(limit, _round_up(n, 8))
    while b > 8 and _round_up(n, b) - n > max(n // 8, 8):
        b = _round_up(b // 2, 8)
    return max(b, 8)


def _blocks_and_pad(S, K, block_q, block_k):
    """One place for the block-pick + round-up policy so forward, public
    API, and chunk-backward can never diverge.  Returns (bq, bk, padq,
    padk): the chosen blocks and seq-dim padding closures."""
    bq = _pick_block(block_q, S)
    bk = _pick_block(block_k, K)
    Sp, Kp = _round_up(S, bq), _round_up(K, bk)

    def padq(x):
        if Sp == S:
            return x
        pad = [(0, 0)] * x.ndim
        pad[2] = (0, Sp - S)
        return jnp.pad(x, pad)

    def padk(x):
        if Kp == K:
            return x
        pad = [(0, 0)] * x.ndim
        pad[2] = (0, Kp - K)
        return jnp.pad(x, pad)

    return bq, bk, padq, padk


def flash_attention_fwd_lse(q, k, v, causal: bool = False,
                            sm_scale: Optional[float] = None,
                            q_position_offset: int = 0,
                            block_q: Optional[int] = None,
                            block_k: Optional[int] = None):
    """Forward-only kernel run returning ``(out, lse)`` — the building
    block ring attention's custom_vjp forward uses to merge per-chunk
    partials (sequence_parallel.py).  Not differentiable on its own.
    Blocks default to the autotuner; pass them explicitly to pin."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    return _fwd_tuned(q, k, v, causal=causal, sm_scale=float(sm_scale),
                      q_offset=int(q_position_offset), kv_len=int(k.shape[2]),
                      block_q=block_q, block_k=block_k)


def flash_attention_bwd_chunk(q, k, v, out, lse, do, causal: bool = False,
                              sm_scale: Optional[float] = None,
                              q_position_offset: int = 0,
                              block_q: Optional[int] = None,
                              block_k: Optional[int] = None,
                              delta=None):
    """One chunk's flash-2 backward given the GLOBAL (merged) out/lse for
    the local q rows: returns this (q, kv-chunk) pair's additive
    contributions (dq_partial, dk, dv) — exact because with
    p = exp(s − lse_global) the backward is linear over kv chunks.  Ring
    attention's custom_vjp backward sums these around the ring; it passes
    the loop-invariant ``delta = rowsum(dO·O)`` so it is computed once,
    not once per ring step.  Blocks default to the autotuner (dq and
    dk/dv resolve independently); pass them explicitly to pin both."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    S, K = q.shape[2], k.shape[2]
    # the kernels re-pad to their own blocks; normalize stats to length S
    lse = lse[:, :, :S]
    if delta is None:
        delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    delta = delta[:, :, :S]
    kw = dict(causal=causal, sm_scale=float(sm_scale),
              q_offset=int(q_position_offset), kv_len=int(K),
              block_q=block_q, block_k=block_k)
    dq = _dq_tuned(q, k, v, do, lse, delta, **kw)
    dk, dv = _dkv_tuned(q, k, v, do, lse, delta, **kw)
    return dq[:, :, :S], dk[:, :, :K], dv[:, :, :K]


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    q_position_offset: int = 0):
    """Memory-efficient attention.

    Args are [batch, num_heads, seq, head_dim] (q may have a different seq
    than k/v).  ``q_position_offset`` is the global position of q's first
    row — used by ring attention, where the local q chunk sits at an offset
    into the global sequence for causal masking; any offset is exact (no
    block alignment required).

    Any shape takes the kernel path: ragged sequence lengths are padded up
    to block multiples and the kernels mask padded key positions, so there
    is no O(S²) fallback.

    Block sizes default to the autotuner (``ops.autotune``): a measured
    search on TPU — the forward and both backward kernels pick their tile
    sizes independently, memoized persistently per shape bucket — and the
    512-block heuristic elsewhere (512s measured fastest on v5e at 32k:
    ~34 TFLOP/s effective causal fwd; 128-blocks were 4× slower).  Pass
    ``block_q``/``block_k`` explicitly to pin all three kernels.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    S, K = q.shape[2], k.shape[2]
    tuned = block_q is None and block_k is None
    if tuned:
        cfg = _fwd_tuned.config(q, k, v, causal=causal,
                                sm_scale=float(sm_scale),
                                q_offset=int(q_position_offset),
                                kv_len=int(K))
        block_q, block_k = cfg["block_q"], cfg["block_k"]
    else:
        block_q = 512 if block_q is None else block_q
        block_k = 512 if block_k is None else block_k
    bq = _pick_block(block_q, S)
    bk = _pick_block(block_k, K)
    Sp = _round_up(S, bq)
    Kp = _round_up(K, bk)
    qp = q if Sp == S else jnp.pad(q, ((0, 0), (0, 0), (0, Sp - S), (0, 0)))
    kp = k if Kp == K else jnp.pad(k, ((0, 0), (0, 0), (0, Kp - K), (0, 0)))
    vp = v if Kp == K else jnp.pad(v, ((0, 0), (0, 0), (0, Kp - K), (0, 0)))
    out = _flash(qp, kp, vp, causal, float(sm_scale), bq, bk,
                 int(q_position_offset), int(K), tuned)
    return out if Sp == S else out[:, :, :S]

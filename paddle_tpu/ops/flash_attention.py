"""Flash attention — Pallas TPU kernels, O(S) memory forward AND backward.

New capability (SURVEY §5: the reference has NO long-context support — no
flash/blockwise attention anywhere in the tree; its attention is the naive
matmul+softmax in python/paddle/nn/layer/transformer.py).

Design:
* All three kernels (fwd, dq, dk/dv) share one structure: a 3-D grid
  ``(batch·heads, owner-block, reduction-block)`` whose innermost dimension
  streams the *other* sequence through VMEM one block at a time, with the
  owner block's accumulators living in VMEM scratch across those steps.
  Nothing sequence-sized is ever resident: VMEM holds O(block²), HBM holds
  only the inputs/outputs — true O(S) memory at any length (validated at
  32k on v5e, where whole-sequence VMEM residency is impossible).
* **forward** keeps flash-2 online softmax (running max/sum, one rescale
  per block); saves per-row logsumexp, laid out ``[BH, S, 1]`` so stats
  load as native (block, 1) tiles — no 1-D→2-D vector reshapes, which
  Mosaic cannot legalize for some dtypes.
* **backward** is the flash-2 recurrence: ``delta = rowsum(dO·O)`` is one
  fused XLA elementwise-reduce; the dq kernel owns a q-block and streams
  kv; the dk/dv kernel owns a kv-block and streams q — each grid step owns
  its output tile outright, so there is no cross-step accumulation in HBM
  and no [B,H,S,block_k] score tile ever materializes.
* Causal masking predicates away the COMPUTE of tiles above the diagonal
  via ``pl.when`` (the BlockSpec pipeline still streams their k/v DMA — a
  known ~2x bandwidth headroom for a future triangle-grid layout); the
  q-position offset (ring attention) is taken in ELEMENTS, so any offset
  is exact.
* **ragged shapes pad-and-mask instead of falling back**: q/k/v pad up to
  block multiples and the kernels mask key positions ≥ the true kv length
  (-inf scores), so ANY shape takes the kernel path — the silent O(S²)
  fallback cliff is gone.
* On non-TPU backends the kernels run in Pallas interpret mode, so tests
  validate the exact kernel code path against the numpy oracle.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG_INF = -jnp.inf


def _naive_reference(q, k, v, causal, sm_scale, q_offset=0):
    """[B,H,S,d] reference (tests only)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
    if causal:
        S, K = s.shape[-2], s.shape[-1]
        q_pos = q_offset + jnp.arange(S)
        mask = q_pos[:, None] >= jnp.arange(K)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    # fully-masked rows (ring chunks ahead of the diagonal) → zero output
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isneginf(s).all(-1, keepdims=True), 0.0, p)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _mask_scores(s, qi, ki, block_q, block_k, causal, q_offset, kv_len,
                 kv_seq):
    """kv-padding + causal masks for a [block_q, block_k] score tile.
    All index math pinned to i32: the package enables jax x64, which would
    otherwise promote Python ints to i64 and break Mosaic."""
    i32 = jnp.int32
    k_pos = ki * i32(block_k) + jax.lax.broadcasted_iota(i32, s.shape, 1)
    if kv_len < kv_seq:  # padded keys masked out
        s = jnp.where(k_pos < i32(kv_len), s, _NEG_INF)
    if causal:
        q_pos = i32(q_offset) + qi * i32(block_q) + \
            jax.lax.broadcasted_iota(i32, s.shape, 0)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return s


def _causal_run(qi, ki, block_q, block_k, q_offset, causal):
    """False iff the whole tile sits above the causal diagonal."""
    if not causal:
        return True
    i32 = jnp.int32
    last_q = i32(q_offset) + (qi + i32(1)) * i32(block_q) - i32(1)
    return ki * i32(block_k) <= last_q


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, kv_seq: int, kv_len: int, block_k: int, causal: bool,
                sm_scale: float, q_offset: int):
    i32 = jnp.int32
    qi = pl.program_id(1).astype(i32)
    ki = pl.program_id(2).astype(i32)
    nk = pl.num_programs(2)
    block_q = q_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(_causal_run(qi, ki, block_q, block_k, q_offset, causal))
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        s = _mask_scores(s, qi, ki, block_q, block_k, causal, q_offset,
                         kv_len, kv_seq)
        m_prev = m_scr[:, :1]                      # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # guard fully-masked rows: exp(-inf − -inf) would be nan
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe)
        alpha = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_safe))
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _fin():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l == 0.0, _NEG_INF, m_scr[:, :1] + jnp.log(l_safe))
        lse_ref[0] = lse.astype(jnp.float32)


def _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k, q_offset,
                kv_len):
    B, H, S, D = q.shape
    K = k.shape[2]
    qs = q.reshape(B * H, S, D)
    ks = k.reshape(B * H, K, D)
    vs = v.reshape(B * H, K, D)

    _I0 = np.int32(0)  # index maps must stay i32 under global x64

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, kv_seq=K, kv_len=kv_len,
                          block_k=block_k, causal=causal, sm_scale=sm_scale,
                          q_offset=q_offset),
        grid=(B * H, S // block_q, K // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            # lse [BH, S, 1]: (block_q, 1) tiles — last dim full, no
            # 1-D vector reshapes anywhere
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
            pltpu.VMEM((block_q, D), jnp.float32),    # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=jax.default_backend() != "tpu",
    )(qs, ks, vs)
    return out.reshape(B, H, S, D), lse.reshape(B, H, S)


# ---------------------------------------------------------------------------
# backward (flash-2 recurrence)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_scr, *, kv_seq: int, kv_len: int, block_k: int,
                   causal: bool, sm_scale: float, q_offset: int):
    i32 = jnp.int32
    qi = pl.program_id(1).astype(i32)
    ki = pl.program_id(2).astype(i32)
    nk = pl.num_programs(2)
    block_q = q_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(_causal_run(qi, ki, block_q, block_k, q_offset, causal))
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        # fully-masked rows: lse = -inf AND every score -inf; replacing
        # lse with 0 makes p = exp(-inf − 0) = 0 with no bool broadcast
        lse = lse_ref[0]                           # (bq, 1)
        lse = jnp.where(jnp.isneginf(lse), 0.0, lse)
        delta = delta_ref[0]                       # (bq, 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        s = _mask_scores(s, qi, ki, block_q, block_k, causal, q_offset,
                         kv_len, kv_seq)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        acc_scr[...] = acc_scr[...] + jnp.dot(
            ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _fin():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, block_q: int,
                    causal: bool, sm_scale: float, q_offset: int,
                    kv_len: int, kv_seq: int):
    i32 = jnp.int32
    ki = pl.program_id(1).astype(i32)
    qi = pl.program_id(2).astype(i32)
    nq = pl.num_programs(2)
    block_k = k_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(_causal_run(qi, ki, block_q, block_k, q_offset, causal))
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                           # (bq, 1)
        lse = jnp.where(jnp.isneginf(lse), 0.0, lse)  # see dq kernel note
        delta = delta_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        s = _mask_scores(s, qi, ki, block_q, block_k, causal, q_offset,
                         kv_len, kv_seq)
        p = jnp.exp(s - lse)
        dv_scr[...] = dv_scr[...] + jnp.dot(
            p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[...] = dk_scr[...] + jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _fin():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, o, lse, do, causal, sm_scale, block_q, block_k,
                q_offset, kv_len):
    B, H, S, D = q.shape
    K = k.shape[2]
    qs = q.reshape(B * H, S, D)
    ks = k.reshape(B * H, K, D)
    vs = v.reshape(B * H, K, D)
    dos = do.reshape(B * H, S, D)
    lses = lse.reshape(B * H, S, 1)
    # delta = rowsum(dO ⊙ O): one fused elementwise+reduce at the XLA level
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1)
    deltas = delta.reshape(B * H, S, 1)

    _I0 = np.int32(0)
    interpret = jax.default_backend() != "tpu"

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, kv_seq=K, kv_len=kv_len,
                          block_k=block_k, causal=causal, sm_scale=sm_scale,
                          q_offset=q_offset),
        grid=(B * H, S // block_q, K // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, _I0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qs, ks, vs, dos, lses, deltas)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, causal=causal,
                          sm_scale=sm_scale, q_offset=q_offset,
                          kv_len=kv_len, kv_seq=K),
        grid=(B * H, K // block_k, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, _I0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, _I0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, K, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, K, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qs, ks, vs, dos, lses, deltas)

    return (dq.reshape(B, H, S, D), dk.reshape(B, H, K, D),
            dv.reshape(B, H, K, D))


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, q_offset, kv_len):
    out, _ = _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k,
                         q_offset, kv_len)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, q_offset,
               kv_len):
    out, lse = _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k,
                           q_offset, kv_len)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, q_offset, kv_len, res,
               do):
    q, k, v, out, lse = res
    return _bwd_pallas(q, k, v, out, lse, do, causal, sm_scale, block_q,
                       block_k, q_offset, kv_len)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _round_up(x, m):
    return (x + m - 1) // m * m


def _pick_block(limit, n):
    """Largest block ≤ limit whose padding waste on a length-n sequence is
    ≤ max(n/8, 8) rows — e.g. S=600 takes 128-blocks (pad 40) rather than
    512-blocks (pad 424 = 70% wasted FLOPs)."""
    b = min(limit, _round_up(n, 8))
    while b > 8 and _round_up(n, b) - n > max(n // 8, 8):
        b = _round_up(b // 2, 8)
    return max(b, 8)


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    q_position_offset: int = 0):
    """Memory-efficient attention.

    Args are [batch, num_heads, seq, head_dim] (q may have a different seq
    than k/v).  ``q_position_offset`` is the global position of q's first
    row — used by ring attention, where the local q chunk sits at an offset
    into the global sequence for causal masking; any offset is exact (no
    block alignment required).

    Any shape takes the kernel path: ragged sequence lengths are padded up
    to block multiples and the kernels mask padded key positions, so there
    is no O(S²) fallback.  Default 512-blocks measured fastest on v5e
    (~34 TFLOP/s effective causal fwd at 32k; 128-blocks were 4× slower).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    S, K = q.shape[2], k.shape[2]
    bq = _pick_block(block_q, S)
    bk = _pick_block(block_k, K)
    Sp = _round_up(S, bq)
    Kp = _round_up(K, bk)
    qp = q if Sp == S else jnp.pad(q, ((0, 0), (0, 0), (0, Sp - S), (0, 0)))
    kp = k if Kp == K else jnp.pad(k, ((0, 0), (0, 0), (0, Kp - K), (0, 0)))
    vp = v if Kp == K else jnp.pad(v, ((0, 0), (0, 0), (0, Kp - K), (0, 0)))
    out = _flash(qp, kp, vp, causal, float(sm_scale), bq, bk,
                 int(q_position_offset), int(K))
    return out if Sp == S else out[:, :, :S]

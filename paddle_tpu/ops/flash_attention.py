"""Flash attention — Pallas TPU kernel with O(S) memory.

New capability (SURVEY §5: the reference has NO long-context support — no
flash/blockwise attention anywhere in the tree; its attention is the naive
matmul+softmax in python/paddle/nn/layer/transformer.py).

Design:
* **forward**: a Pallas kernel tiled (batch·heads, q-blocks) with an online-
  softmax inner loop over kv-blocks — scores never materialize in HBM; the
  running max/sum live in VMEM scratch.  MXU-shaped blocks (128×128 default).
* **backward**: custom_vjp, blockwise at the XLA level (lax.scan over
  kv-blocks) using the saved logsumexp — the standard flash-2 dq/dk/dv
  recurrence.  O(S) memory, fuses well, and is backend-portable (the CPU
  test mesh runs the same code).
* On non-TPU backends the forward kernel runs in Pallas interpret mode, so
  tests validate the exact kernel code path against the numpy oracle.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = ["flash_attention"]


def _naive_reference(q, k, v, causal, sm_scale, q_offset=0):
    """[B,H,S,d] reference (tests + ragged-shape fallback)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
    if causal:
        S, K = s.shape[-2], s.shape[-1]
        q_pos = q_offset + jnp.arange(S)
        mask = q_pos[:, None] >= jnp.arange(K)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    # fully-masked rows (ring chunks ahead of the diagonal) → zero output
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isneginf(s).all(-1, keepdims=True), 0.0, p)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, kv_seq: int,
                block_k: int, causal: bool, sm_scale: float, q_offset_blocks: int):
    # all index math pinned to i32: the package enables jax x64, which would
    # otherwise promote Python-int constants to i64 and break Mosaic
    i32 = jnp.int32
    qi = pl.program_id(1).astype(i32)
    q = q_ref[0].astype(jnp.float32)  # [block_q, d]
    block_q = q.shape[0]

    m = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)

    num_k = kv_seq // block_k

    def body(ki, carry):
        ki = ki.astype(i32)
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * i32(block_k), block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * i32(block_k), block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = (qi + i32(q_offset_blocks)) * i32(block_q) + \
                jax.lax.broadcasted_iota(i32, (block_q, block_k), 0)
            k_pos = ki * i32(block_k) + jax.lax.broadcasted_iota(
                i32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # guard fully-masked rows: exp(-inf - -inf) would be nan
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe[:, None])
        alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return m_new, l, acc

    if causal:
        # skip kv-blocks entirely above the diagonal
        last = qi + i32(q_offset_blocks) + i32(1)
        num_k_eff = jnp.minimum(
            i32(num_k),
            (last * i32(block_q) + i32(block_k - 1)) // i32(block_k))
    else:
        num_k_eff = i32(num_k)
    m, l, acc = jax.lax.fori_loop(i32(0), num_k_eff, body, (m, l, acc))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse = jnp.where(l == 0.0, -jnp.inf, m + jnp.log(l_safe))
    lse_ref[0, 0] = lse.astype(jnp.float32)


def _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k, q_offset):
    B, H, S, D = q.shape
    K = k.shape[2]
    block_q = min(block_q, S)
    block_k = min(block_k, K)
    grid = (B * H, S // block_q)

    qs = q.reshape(B * H, S, D)
    ks = k.reshape(B * H, K, D)
    vs = v.reshape(B * H, K, D)

    kernel = functools.partial(
        _fwd_kernel, kv_seq=K, block_k=block_k, causal=causal,
        sm_scale=sm_scale, q_offset_blocks=q_offset // block_q)

    _I0 = np.int32(0)  # np scalar: index maps may not capture device arrays

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        # index-map constants MUST be i32: under the package's global x64
        # mode a literal 0 traces as i64 and Mosaic fails to legalize the
        # index computation (func.return (i32, i32, i64))
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, _I0)),
            pl.BlockSpec((1, K, D), lambda b, i: (b, _I0, _I0)),
            pl.BlockSpec((1, K, D), lambda b, i: (b, _I0, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, _I0)),
            # lse as [BH, 1, S]: block (1,1,block_q) satisfies the TPU
            # (8,128)-divisible-or-full tiling rule on the last two dims
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, _I0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, 1, S), jnp.float32),
        ],
        interpret=jax.default_backend() != "tpu",
    )(qs, ks, vs)
    return out.reshape(B, H, S, D), lse.reshape(B, H, S)


# ---------------------------------------------------------------------------
# backward (blockwise XLA, flash-2 recurrence)
# ---------------------------------------------------------------------------
def _bwd_blockwise(q, k, v, o, lse, do, causal, sm_scale, block_k, q_offset):
    B, H, S, Dh = q.shape
    K = k.shape[2]
    block_k = min(block_k, K)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = (dof * o.astype(jnp.float32)).sum(axis=-1)  # [B,H,S]

    q_pos = q_offset + jnp.arange(S)

    def scan_body(carry, kv_block):
        dq = carry
        kb, vb, kstart = kv_block  # [B,H,block_k,D]
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb) * sm_scale
        if causal:
            k_pos = kstart + jnp.arange(block_k)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, -jnp.inf)
        p = jnp.exp(s - lse[..., None])  # [B,H,S,block_k]
        p = jnp.where(jnp.isneginf(lse[..., None]), 0.0, p)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vb)
        ds = p * (dp - delta[..., None]) * sm_scale
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kb)
        return dq, (dk, dv)

    nb = K // block_k
    kb = kf.reshape(B, H, nb, block_k, Dh).transpose(2, 0, 1, 3, 4)
    vb = vf.reshape(B, H, nb, block_k, Dh).transpose(2, 0, 1, 3, 4)
    kstarts = jnp.arange(nb) * block_k
    dq, (dks, dvs) = jax.lax.scan(
        scan_body, jnp.zeros(q.shape, jnp.float32), (kb, vb, kstarts))
    dk = dks.transpose(1, 2, 0, 3, 4).reshape(B, H, K, Dh)
    dv = dvs.transpose(1, 2, 0, 3, 4).reshape(B, H, K, Dh)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, q_offset):
    out, _ = _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k, q_offset)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, q_offset):
    out, lse = _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k, q_offset)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, q_offset, res, do):
    q, k, v, out, lse = res
    return _bwd_blockwise(q, k, v, out, lse, do, causal, sm_scale, block_k,
                          q_offset)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    q_position_offset: int = 0):
    """Memory-efficient attention.

    Args are [batch, num_heads, seq, head_dim] (q may have a different seq
    than k/v).  ``q_position_offset`` is the global position of q's first
    row — used by ring attention, where the local q chunk sits at an offset
    into the global sequence for causal masking.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    S, K = q.shape[2], k.shape[2]
    bq = min(block_q, S)
    bk = min(block_k, K)
    if S % bq or K % bk or (causal and q_position_offset % bq):
        # ragged tail — or a causal offset that isn't q-block-aligned: the
        # forward kernel floors the offset to whole q-blocks
        # (q_offset_blocks), which would mis-mask and disagree with the
        # exact-offset backward.  The reference path is exact for any shape.
        return _naive_reference(q, k, v, causal, sm_scale, q_position_offset)
    return _flash(q, k, v, causal, float(sm_scale), bq, bk,
                  int(q_position_offset))

"""Measured Pallas kernel autotuning with a persistent on-disk cache.

The hand kernels in this package ship tile-size defaults that were tuned
on one shape class (flash attention's 512-blocks on 32k sequences, the
conv+BN epilogue's 512x256 on ResNet layers).  FlashAttention-class
kernels are famously block-size-sensitive, and the measured gap is real:
BENCH_r04 has ResNet-50 at 0.17 MFU and 32k causal flash at 0.38 while
BERT reaches 0.50.  The Triton/AutoTVM answer — a small template space,
compile + time each candidate on the real shapes, memoize the winner —
is what this module provides, TPU-native:

* candidate generators respect Mosaic's (8, 128) f32 tile (sublane
  multiples of 8, lane multiples of 128) and a VMEM-footprint estimate,
  so every candidate can actually lower;
* the search runs on the REAL backend with synthetic data of the real
  shapes/dtypes; off-TPU (interpret mode, CI) the registered heuristic
  default is returned without timing — interpret-mode timings would tune
  for the wrong machine;
* winners are memoized in-process and in a JSON cache keyed by
  ``(kernel, shape bucket, dtype, device kind)`` so training restarts and
  serving engines pay zero re-tuning (``FLAGS_kernel_tuning_cache``);
* every resolution publishes an ``("autotune", kernel)`` event on
  ``framework.trace_events`` (hit / disk_hit / search / heuristic, plus
  counter snapshots) — ``analysis.RetraceMonitor`` turns a measured
  search after ``mark_warm()`` into rule K701, the serving-hot-path twin
  of R403/S601 — and a "Kernel autotune" section rides along in
  ``profiler.summary()``.

Usage::

    @autotune("my_kernel", params=("block_m",), space=my_space,
              heuristic=lambda x: {"block_m": 512})
    def _my_kernel(x, *, block_m):
        return pl.pallas_call(...)(x)

    _my_kernel(x)                  # tuned (or heuristic off-TPU)
    _my_kernel(x, block_m=128)     # explicit override, no tuning
    _my_kernel.config(x)           # resolve the config without running
"""
from __future__ import annotations

import functools
import json
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework import trace_events
from ..framework.errors import InvalidArgumentError
from ..framework.flags import flag

__all__ = [
    "autotune", "TunedKernel", "tile_candidates", "vmem_fits",
    "cache_path", "clear_cache", "get_counters", "reset_counters",
    "mark_warm", "is_warm", "registered_kernels", "fused_epilogues_eligible",
]

# -- Mosaic tiling / VMEM constants ------------------------------------------
SUBLANE = 8      # f32 sublane tile; candidate row blocks are multiples
LANE = 128       # lane tile; candidate column blocks are multiples
VMEM_BYTES = 16 * 1024 * 1024  # per-core VMEM (v4/v5e/v5p all ~16 MB)
#: fraction of VMEM a candidate's resident blocks may claim — the rest is
#: double-buffering headroom for the pipelined DMA in/out streams
VMEM_BUDGET_FRAC = 0.7

_lock = threading.RLock()
_REGISTRY: Dict[str, "TunedKernel"] = {}
_mem_cache: Dict[str, dict] = {}          # key -> config (measured or disk)
_heuristic_cache: Dict[str, dict] = {}    # key -> config (untimed fallback)
_counters: Dict[str, Dict[str, int]] = {}
_warm = False                              # set by serving warmup; see K701

_disk_state = {"path": None, "entries": None}  # lazily-loaded JSON cache


def _next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def _round_up(x: int, m: int) -> int:
    return -(-int(x) // m) * m


def tile_candidates(n: int, *, multiple: int = SUBLANE,
                    base: Sequence[int] = (64, 128, 256, 512, 1024),
                    ) -> List[int]:
    """Candidate block sizes for a length-``n`` dimension: the power-of-two
    ladder clamped to the PADDED length (``round_up(n, multiple)``) so a
    short dimension — a serving bucket, a small model — never pays
    full-width padded tiles, each rounded to the Mosaic ``multiple``."""
    if n <= 0:
        raise InvalidArgumentError(f"tile_candidates: bad dim {n}")
    cap = _round_up(n, multiple)
    out = sorted({max(multiple, min(_round_up(b, multiple), cap))
                  for b in base})
    return out


def vmem_fits(nbytes: int, frac: float = VMEM_BUDGET_FRAC) -> bool:
    """True iff a candidate's resident VMEM blocks fit the budget."""
    return nbytes <= int(VMEM_BYTES * frac)


def _bucket_shape(shape) -> Tuple[int, ...]:
    """Shape bucket for the cache key: each dim rounds up to a power of
    two, so nearby geometries (ragged batches, serving buckets) share one
    tuning entry.  The kernels clamp blocks to the real shape at call
    time, so a winner from a larger bucket member stays valid."""
    return tuple(_next_pow2(d) for d in shape)


def _device_kind() -> str:
    import jax

    try:
        return jax.devices()[0].device_kind
    except Exception:  # backend not initialized / unreachable
        return jax.default_backend()


def _is_arraylike(a) -> bool:
    return hasattr(a, "shape") and hasattr(a, "dtype")


# -- persistent cache --------------------------------------------------------
def cache_path() -> Optional[str]:
    """Resolved on-disk cache path (``FLAGS_kernel_tuning_cache``), or
    ``None`` when persistence is disabled."""
    val = str(flag("kernel_tuning_cache") or "").strip()
    if val.lower() in ("0", "off", "none", "false", "disabled"):
        return None
    if not val:
        return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                            "kernel_tuning.json")
    return val


def _disk_entries() -> Dict[str, dict]:
    """The loaded disk cache, reloaded when the flag re-points it."""
    path = cache_path()
    if path is None:
        return {}
    if _disk_state["path"] != path or _disk_state["entries"] is None:
        entries = {}
        try:
            with open(path) as f:
                data = json.load(f)
            if isinstance(data, dict):
                entries = {k: v for k, v in data.get("entries", {}).items()
                           if isinstance(v, dict) and "config" in v}
        except (OSError, ValueError):
            entries = {}
        _disk_state["path"] = path
        _disk_state["entries"] = entries
    return _disk_state["entries"]


def _disk_store(key: str, kernel: str, config: dict, best_ms: float) -> None:
    path = cache_path()
    if path is None:
        return
    entries = dict(_disk_entries())
    # merge with concurrent writers: reread before rewrite
    try:
        with open(path) as f:
            on_disk = json.load(f).get("entries", {})
        if isinstance(on_disk, dict):
            entries = {**on_disk, **entries}
    except (OSError, ValueError):
        pass
    entries[key] = {"kernel": kernel, "config": dict(config),
                    "best_ms": round(float(best_ms), 4)}
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"version": 1, "entries": entries}, f, indent=0,
                      sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        return  # read-only cache dir: winners stay process-local
    _disk_state["path"] = path
    _disk_state["entries"] = entries


def clear_cache(memory: bool = True, disk: bool = False) -> None:
    """Drop tuned winners.  ``disk=True`` also deletes the JSON file."""
    with _lock:
        if memory:
            _mem_cache.clear()
            _heuristic_cache.clear()
        _disk_state["path"] = None
        _disk_state["entries"] = None
    if disk:
        path = cache_path()
        if path is not None:
            try:
                os.remove(path)
            except OSError:
                pass


# -- counters / events -------------------------------------------------------
_COUNTER_KEYS = ("hits", "disk_hits", "searches", "heuristic",
                 "configs_timed", "search_failures", "searches_after_warm")


def _bump(kernel: str, field: str, n: int = 1) -> Dict[str, int]:
    c = _counters.setdefault(kernel, {k: 0 for k in _COUNTER_KEYS})
    c[field] += n
    return c


def get_counters(kernel: Optional[str] = None) -> Dict:
    """Counter snapshot(s): one kernel's dict, or ``{kernel: dict}``."""
    with _lock:
        if kernel is not None:
            return dict(_counters.get(kernel,
                                      {k: 0 for k in _COUNTER_KEYS}))
        return {k: dict(v) for k, v in _counters.items()}


def reset_counters() -> None:
    with _lock:
        _counters.clear()


def mark_warm() -> None:
    """Declare tuning warmup over (serving engines call this after
    ``warmup()``): any measured search past this point is tuning work on
    a hot path — a cache miss the pre-warmed JSON cache should have
    absorbed — and is flagged by analysis rule K701."""
    global _warm
    with _lock:
        _warm = True


def is_warm() -> bool:
    return _warm


def _publish(kernel: str, event: str, key: str, config: dict, **extra):
    with _lock:
        counters = dict(_counters.get(kernel,
                                      {k: 0 for k in _COUNTER_KEYS}))
        warm = _warm
    if trace_events.active():
        info = {"event": event, "key": key, "config": dict(config),
                "warm": warm, "counters": counters}
        info.update(extra)
        trace_events.notify(("autotune", kernel), info)


def registered_kernels() -> List[str]:
    return sorted(_REGISTRY)


# -- measured search ---------------------------------------------------------
def _synthetic_args(args):
    """Concrete stand-ins mirroring each array arg's shape/dtype (the real
    args may be tracers when tuning triggers inside a jit trace): floats
    draw standard normal, ints are zeros (always in-range for labels)."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    out = []
    for a in args:
        if _is_arraylike(a):
            dt = np.dtype(a.dtype)  # ml_dtypes (bfloat16 etc.) included
            if np.issubdtype(dt, np.integer):
                out.append(jnp.zeros(tuple(a.shape), dtype=dt))
            else:
                out.append(jnp.asarray(
                    rng.standard_normal(tuple(a.shape)).astype(np.float32),
                    dtype=dt))
        else:
            out.append(a)
    return out


def _time_once(fn, args) -> float:
    """Compile + best-of-3 wall time (ms) for one candidate."""
    import jax

    jitted = jax.jit(fn)
    jax.block_until_ready(jitted(*args))  # compile + warm
    best = math.inf
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(jitted(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


class TunedKernel:
    """A kernel whose tile parameters the autotuner owns.

    ``fn(*args, **kwargs, **config)`` is the measurable unit — it must
    accept the config params as keyword arguments and run end-to-end
    (including any padding the config implies).  ``space(*args,
    **kwargs)`` yields candidate config dicts (already Mosaic-aligned and
    VMEM-filtered); ``heuristic(*args, **kwargs)`` is the untimed default
    — it MUST reproduce the kernel's pre-autotuner behavior so the
    default config stays bit-compatible.  ``key_kwargs`` names the
    non-array kwargs that change the compiled kernel (e.g. ``causal``)
    and so belong in the cache key."""

    def __init__(self, fn: Callable, name: str, params: Tuple[str, ...],
                 space: Callable, heuristic: Callable,
                 key_kwargs: Tuple[str, ...] = ()):
        functools.update_wrapper(self, fn)
        self.fn = fn
        self.name = name
        self.params = tuple(params)
        self.space = space
        self.heuristic = heuristic
        self.key_kwargs = tuple(key_kwargs)
        if name in _REGISTRY:
            raise InvalidArgumentError(
                f"autotune kernel {name!r} registered twice")
        _REGISTRY[name] = self

    # -- key -----------------------------------------------------------------
    def cache_key(self, *args, **kwargs) -> str:
        """Stable string key: kernel | per-array (pow2-bucketed shape,
        dtype) | key kwargs | device kind."""
        parts = [self.name]
        for a in args:
            if _is_arraylike(a):
                bucket = "x".join(map(str, _bucket_shape(a.shape)))
                parts.append(f"{bucket}:{np.dtype(a.dtype).name}")
            else:
                parts.append(repr(a))
        for k in self.key_kwargs:
            parts.append(f"{k}={kwargs.get(k)!r}")
        parts.append(_device_kind())
        return "|".join(parts)

    def candidates(self, *args, **kwargs) -> List[dict]:
        """The (deduped) candidate configs for these args; the heuristic
        default is always in the running."""
        kw = {k: v for k, v in kwargs.items() if k not in self.params}
        cands = list(self.space(*args, **kw))
        default = self.heuristic(*args, **kw)
        seen, out = set(), []
        for c in cands + [default]:
            c = {k: int(v) if isinstance(v, (bool, np.integer)) or
                 isinstance(v, int) else v for k, v in c.items()}
            sig = tuple(sorted(c.items()))
            if sig not in seen:
                seen.add(sig)
                out.append(c)
        return out

    # -- resolution ----------------------------------------------------------
    def config(self, *args, **kwargs) -> dict:
        """Resolve the config for these args without running the kernel:
        in-memory hit -> disk hit -> measured search (TPU, or mode
        'force') -> heuristic default."""
        import jax

        kw = {k: v for k, v in kwargs.items() if k not in self.params}
        key = self.cache_key(*args, **kw)
        mode = str(flag("kernel_autotune")).lower()
        measurable = mode == "force" or (
            mode != "off" and jax.default_backend() == "tpu")

        with _lock:
            cfg = _mem_cache.get(key)
            if cfg is None and not measurable:
                cfg = _heuristic_cache.get(key)
            if cfg is not None:
                _bump(self.name, "hits")
        if cfg is not None:
            _publish(self.name, "hit", key, cfg)
            return dict(cfg)

        if measurable:
            disk = _disk_entries().get(key)
            if disk is not None:
                cfg = dict(disk["config"])
                with _lock:
                    _mem_cache[key] = cfg
                    _bump(self.name, "disk_hits")
                _publish(self.name, "disk_hit", key, cfg)
                return dict(cfg)
            return self._search(key, args, kw)

        cfg = self.heuristic(*args, **kw)
        with _lock:
            _heuristic_cache[key] = dict(cfg)
            _bump(self.name, "heuristic")
        _publish(self.name, "heuristic", key, cfg)
        return dict(cfg)

    def _search(self, key: str, args, kw) -> dict:
        from .. import profiler

        cands = self.candidates(*args, **kw)
        default = self.heuristic(*args, **kw)
        synth = _synthetic_args(args)
        best_cfg, best_ms, timed = dict(default), math.inf, 0
        with profiler.RecordEvent(f"autotune/{self.name}"):
            for cand in cands:
                merged = {**kw, **cand}
                try:
                    ms = _time_once(
                        lambda *a, _m=merged: self.fn(*a, **_m), synth)
                except Exception:  # candidate fails to lower: skip it
                    with _lock:
                        _bump(self.name, "search_failures")
                    continue
                timed += 1
                if ms < best_ms:
                    best_cfg, best_ms = dict(cand), ms
        if timed == 0:  # nothing lowered — fall back, don't poison caches
            with _lock:
                _bump(self.name, "heuristic")
            _publish(self.name, "heuristic", key, default,
                     note="all candidates failed")
            return dict(default)
        with _lock:
            _mem_cache[key] = dict(best_cfg)
            _bump(self.name, "searches")
            _bump(self.name, "configs_timed", timed)
            if _warm:
                _bump(self.name, "searches_after_warm")
        _disk_store(key, self.name, best_cfg, best_ms)
        _publish(self.name, "search", key, best_cfg,
                 best_ms=round(best_ms, 4), n_candidates=len(cands),
                 n_timed=timed)
        return dict(best_cfg)

    # -- call ----------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        overrides = {k: kwargs.pop(k) for k in self.params
                     if kwargs.get(k) is not None}
        for k in self.params:
            kwargs.pop(k, None)  # drop explicit Nones
        if len(overrides) < len(self.params):
            cfg = self.config(*args, **kwargs)
            cfg.update(overrides)
        else:
            cfg = overrides
        return self.fn(*args, **kwargs, **cfg)

    def __repr__(self):
        return f"<TunedKernel {self.name} params={self.params}>"


def autotune(name: str, *, params: Sequence[str], space: Callable,
             heuristic: Callable, key_kwargs: Sequence[str] = ()):
    """Register ``fn`` as an autotuned kernel (see :class:`TunedKernel`)."""

    def deco(fn):
        return TunedKernel(fn, name, tuple(params), space, heuristic,
                           tuple(key_kwargs))

    return deco


# -- model-integration gate --------------------------------------------------
def fused_epilogues_eligible(feature_dim: Optional[int] = None) -> bool:
    """Should a model hot path call the fused Pallas epilogues?  Mirrors
    the flash-attention gate: a real TPU backend (interpret mode loses),
    lane-aligned feature dim, and no model/sep sharding — ``pallas_call``
    has no GSPMD partitioning rule, so a sharded call would all-gather
    its operands onto every chip."""
    import jax

    if not flag("fused_epilogues") or jax.default_backend() != "tpu":
        return False
    if feature_dim is not None and feature_dim % LANE != 0:
        return False
    from ..distributed.mesh import get_mesh

    mesh = get_mesh()
    return (mesh.shape.get("model", 1) == 1
            and mesh.shape.get("sep", 1) == 1)


# -- profiler summary section ------------------------------------------------
_section_base: Dict[str, Dict[str, int]] = {}


def _on_profiler_reset() -> None:
    with _lock:
        _section_base.clear()
        _section_base.update({k: dict(v) for k, v in _counters.items()})


def _summary_section() -> str:
    """Counter deltas since the profiler was last reset, as a table the
    ``profiler.summary()`` host-event report appends."""
    with _lock:
        rows = []
        for kernel in sorted(_counters):
            base = _section_base.get(kernel, {})
            d = {k: _counters[kernel][k] - base.get(k, 0)
                 for k in _COUNTER_KEYS}
            if any(d.values()):
                rows.append((kernel, d))
    if not rows:
        return ""
    path = cache_path() or "<in-memory only>"
    w = max(len(r[0]) for r in rows) + 2
    lines = [f"Kernel autotune (cache: {path})",
             f"{'Kernel':<{w}}{'Searches':>10}{'Timed':>8}{'Hits':>8}"
             f"{'Disk':>8}{'Heur':>8}{'AfterWarm':>11}"]
    for kernel, d in rows:
        lines.append(
            f"{kernel:<{w}}{d['searches']:>10}{d['configs_timed']:>8}"
            f"{d['hits']:>8}{d['disk_hits']:>8}{d['heuristic']:>8}"
            f"{d['searches_after_warm']:>11}")
    return "\n".join(lines)


def _register_profiler_section() -> None:
    from .. import profiler

    profiler.register_summary_section(_summary_section,
                                      on_reset=_on_profiler_reset)


_register_profiler_section()

"""Measured Pallas kernel autotuning — the ``"kernel"`` client of the
generic measured-search engine in ``paddle_tpu.tuning.engine``.

The hand kernels in this package ship tile-size defaults that were tuned
on one shape class (flash attention's 512-blocks on 32k sequences, the
conv+BN epilogue's 512x256 on ResNet layers).  FlashAttention-class
kernels are famously block-size-sensitive, and the measured gap is real:
BENCH_r04 has ResNet-50 at 0.17 MFU and 32k causal flash at 0.38 while
BERT reaches 0.50.  The Triton/AutoTVM answer — a small template space,
compile + time each candidate on the real shapes, memoize the winner —
lives in the engine; this module keeps what is kernel-specific:

* candidate generators respect Mosaic's (8, 128) f32 tile (sublane
  multiples of 8, lane multiples of 128) and a VMEM-footprint estimate,
  so every candidate can actually lower;
* the search runs on the REAL backend with synthetic data of the real
  shapes/dtypes; off-TPU (interpret mode, CI) the registered heuristic
  default is returned without timing — interpret-mode timings would tune
  for the wrong machine;
* winners are memoized in-process and in the shared JSON cache keyed by
  ``(kernel, shape bucket, dtype, device kind)`` so training restarts and
  serving engines pay zero re-tuning (``FLAGS_kernel_tuning_cache`` —
  the same file also holds sharding-plan and serving-config winners);
* every resolution publishes an ``("autotune", kernel)`` event on
  ``framework.trace_events`` (hit / disk_hit / search / heuristic, plus
  counter snapshots) — ``analysis.RetraceMonitor`` turns a measured
  search after ``mark_warm()`` into rule K701, the serving-hot-path twin
  of R403/S601 — and a "Measured search" section rides along in
  ``profiler.summary()``.

Usage::

    @autotune("my_kernel", params=("block_m",), space=my_space,
              heuristic=lambda x: {"block_m": 512})
    def _my_kernel(x, *, block_m):
        return pl.pallas_call(...)(x)

    _my_kernel(x)                  # tuned (or heuristic off-TPU)
    _my_kernel(x, block_m=128)     # explicit override, no tuning
    _my_kernel.config(x)           # resolve the config without running
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework.errors import InvalidArgumentError
from ..framework.flags import flag
from ..tuning import engine as _engine
from ..tuning.engine import (  # noqa: F401  (re-exported API)
    _COUNTER_KEYS,
    cache_path,
    clear_cache,
    get_counters,
    is_warm,
    mark_warm,
    measure_ms,
    reset_counters,
    reset_warm,
)

__all__ = [
    "autotune", "TunedKernel", "tile_candidates", "vmem_fits",
    "cache_path", "clear_cache", "get_counters", "reset_counters",
    "mark_warm", "is_warm", "reset_warm", "registered_kernels",
    "fused_epilogues_eligible",
]

# -- Mosaic tiling / VMEM constants ------------------------------------------
SUBLANE = 8      # f32 sublane tile; candidate row blocks are multiples
LANE = 128       # lane tile; candidate column blocks are multiples
VMEM_BYTES = 16 * 1024 * 1024  # per-core VMEM (v4/v5e/v5p all ~16 MB)
#: fraction of VMEM a candidate's resident blocks may claim — the rest is
#: double-buffering headroom for the pipelined DMA in/out streams
VMEM_BUDGET_FRAC = 0.7

_REGISTRY: Dict[str, "TunedKernel"] = {}

_bucket_shape = _engine.bucket_shape
_device_kind = _engine.device_kind


def _round_up(x: int, m: int) -> int:
    return -(-int(x) // m) * m


def tile_candidates(n: int, *, multiple: int = SUBLANE,
                    base: Sequence[int] = (64, 128, 256, 512, 1024),
                    ) -> List[int]:
    """Candidate block sizes for a length-``n`` dimension: the power-of-two
    ladder clamped to the PADDED length (``round_up(n, multiple)``) so a
    short dimension — a serving bucket, a small model — never pays
    full-width padded tiles, each rounded to the Mosaic ``multiple``."""
    if n <= 0:
        raise InvalidArgumentError(f"tile_candidates: bad dim {n}")
    cap = _round_up(n, multiple)
    out = sorted({max(multiple, min(_round_up(b, multiple), cap))
                  for b in base})
    return out


def vmem_fits(nbytes: int, frac: float = VMEM_BUDGET_FRAC) -> bool:
    """True iff a candidate's resident VMEM blocks fit the budget."""
    return nbytes <= int(VMEM_BYTES * frac)


def _is_arraylike(a) -> bool:
    return hasattr(a, "shape") and hasattr(a, "dtype")


def registered_kernels() -> List[str]:
    return sorted(_REGISTRY)


# -- measured search ---------------------------------------------------------
def _synthetic_args(args):
    """Concrete stand-ins mirroring each array arg's shape/dtype (the real
    args may be tracers when tuning triggers inside a jit trace): floats
    draw standard normal, ints are zeros (always in-range for labels)."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    out = []
    for a in args:
        if _is_arraylike(a):
            dt = np.dtype(a.dtype)  # ml_dtypes (bfloat16 etc.) included
            if np.issubdtype(dt, np.integer):
                out.append(jnp.zeros(tuple(a.shape), dtype=dt))
            else:
                out.append(jnp.asarray(
                    rng.standard_normal(tuple(a.shape)).astype(np.float32),
                    dtype=dt))
        else:
            out.append(a)
    return out


def _time_once(fn, args) -> float:
    """Compile + best-of-3 wall time (ms) for one candidate (the untimed
    warm call and best-of-N live in ``engine.measure_ms``)."""
    import jax

    return measure_ms(jax.jit(fn), args, repeats=3)


class TunedKernel:
    """A kernel whose tile parameters the autotuner owns.

    ``fn(*args, **kwargs, **config)`` is the measurable unit — it must
    accept the config params as keyword arguments and run end-to-end
    (including any padding the config implies).  ``space(*args,
    **kwargs)`` yields candidate config dicts (already Mosaic-aligned and
    VMEM-filtered); ``heuristic(*args, **kwargs)`` is the untimed default
    — it MUST reproduce the kernel's pre-autotuner behavior so the
    default config stays bit-compatible.  ``key_kwargs`` names the
    non-array kwargs that change the compiled kernel (e.g. ``causal``)
    and so belong in the cache key."""

    def __init__(self, fn: Callable, name: str, params: Tuple[str, ...],
                 space: Callable, heuristic: Callable,
                 key_kwargs: Tuple[str, ...] = ()):
        functools.update_wrapper(self, fn)
        self.fn = fn
        self.name = name
        self.params = tuple(params)
        self.space = space
        self.heuristic = heuristic
        self.key_kwargs = tuple(key_kwargs)
        if name in _REGISTRY:
            raise InvalidArgumentError(
                f"autotune kernel {name!r} registered twice")
        _REGISTRY[name] = self

    # -- key -----------------------------------------------------------------
    def cache_key(self, *args, **kwargs) -> str:
        """Stable string key: kernel | per-array (pow2-bucketed shape,
        dtype) | key kwargs | device kind."""
        parts = [self.name]
        for a in args:
            if _is_arraylike(a):
                bucket = "x".join(map(str, _bucket_shape(a.shape)))
                parts.append(f"{bucket}:{np.dtype(a.dtype).name}")
            else:
                parts.append(repr(a))
        for k in self.key_kwargs:
            parts.append(f"{k}={kwargs.get(k)!r}")
        parts.append(_device_kind())
        return "|".join(parts)

    def candidates(self, *args, **kwargs) -> List[dict]:
        """The (deduped) candidate configs for these args; the heuristic
        default is always in the running."""
        kw = {k: v for k, v in kwargs.items() if k not in self.params}
        return _engine.dedup_candidates(self.space(*args, **kw),
                                        self.heuristic(*args, **kw))

    # -- resolution ----------------------------------------------------------
    def config(self, *args, **kwargs) -> dict:
        """Resolve the config for these args without running the kernel:
        in-memory hit -> disk hit -> measured search (TPU, or mode
        'force') -> heuristic default."""
        import jax

        kw = {k: v for k, v in kwargs.items() if k not in self.params}
        key = self.cache_key(*args, **kw)
        mode = str(flag("kernel_autotune")).lower()
        measurable = mode == "force" or (
            mode != "off" and jax.default_backend() == "tpu")
        synth = None  # built once, only if a search actually measures

        def measure(cand: dict) -> float:
            nonlocal synth
            if synth is None:
                synth = _synthetic_args(args)
            merged = {**kw, **cand}
            return _time_once(lambda *a, _m=merged: self.fn(*a, **_m),
                              synth)

        return _engine.resolve(
            "kernel", self.name, key,
            candidates=lambda: self.space(*args, **kw),
            measure=measure,
            heuristic=lambda: self.heuristic(*args, **kw),
            measurable=measurable)

    # -- call ----------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        overrides = {k: kwargs.pop(k) for k in self.params
                     if kwargs.get(k) is not None}
        for k in self.params:
            kwargs.pop(k, None)  # drop explicit Nones
        if len(overrides) < len(self.params):
            cfg = self.config(*args, **kwargs)
            cfg.update(overrides)
        else:
            cfg = overrides
        return self.fn(*args, **kwargs, **cfg)

    def __repr__(self):
        return f"<TunedKernel {self.name} params={self.params}>"


def autotune(name: str, *, params: Sequence[str], space: Callable,
             heuristic: Callable, key_kwargs: Sequence[str] = ()):
    """Register ``fn`` as an autotuned kernel (see :class:`TunedKernel`)."""

    def deco(fn):
        return TunedKernel(fn, name, tuple(params), space, heuristic,
                           tuple(key_kwargs))

    return deco


# -- model-integration gate --------------------------------------------------
def fused_epilogues_eligible(feature_dim: Optional[int] = None) -> bool:
    """Should a model hot path call the fused Pallas epilogues?  Mirrors
    the flash-attention gate: a real TPU backend (interpret mode loses),
    lane-aligned feature dim, and no model/sep sharding — ``pallas_call``
    has no GSPMD partitioning rule, so a sharded call would all-gather
    its operands onto every chip."""
    import jax

    if not flag("fused_epilogues") or jax.default_backend() != "tpu":
        return False
    if feature_dim is not None and feature_dim % LANE != 0:
        return False
    from ..distributed.mesh import get_mesh

    mesh = get_mesh()
    return (mesh.shape.get("model", 1) == 1
            and mesh.shape.get("sep", 1) == 1)

"""Fused softmax-cross-entropy — Pallas TPU kernel, no [M, V] prob matrix.

The LM losses (GPT next-token, BERT MLM) compute
``-log_softmax(logits)[label]`` over a vocab-sized axis.  The XLA lowering
materializes the full ``[M, V]`` log-probability tensor in HBM just to
gather one element per row — for GPT at B·S = 8k rows and V = 50k that is
a 1.6 GB write + read whose only purpose is a ``[M]`` gather.  This kernel
streams vocab blocks through VMEM with the flash-attention online-softmax
recurrence (running max + running sum-of-exp) and picks the label logit on
the fly, so nothing vocab-sized is ever written:

    loss[i] = logsumexp(logits[i, :]) - logits[i, label[i]]

The backward needs ``d logits`` — an [M, V] tensor by definition — but it
is produced directly as ``(exp(logits - lse) - onehot) * g`` in one fused
XLA elementwise pass from the saved per-row ``lse``; the probability
matrix still never exists on its own.  Integer labels get a symbolic-zero
(float0) cotangent.

Tile sizes come from ``ops.autotune`` (kernel name "softmax_xent").  The
vocab axis is padded to the block multiple and masked in-kernel, so any V
works (no 128-alignment requirement on the caller).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # jax < 0.6 spells it TPUCompilerParams
    pltpu.CompilerParams = pltpu.TPUCompilerParams

from ..framework.errors import InvalidArgumentError
from . import autotune as _at

__all__ = ["softmax_cross_entropy"]

_NEG_INF = -jnp.inf


def _kernel(x_ref, lab_ref, loss_ref, lse_ref, m_scr, l_scr, acc_scr,
            *, V: int, block_v: int):
    i32 = jnp.int32
    vi = pl.program_id(1).astype(i32)
    nv = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    x = x_ref[...].astype(jnp.float32)               # (bm, bv)
    v_pos = vi * i32(block_v) + jax.lax.broadcasted_iota(i32, x.shape, 1)
    x = jnp.where(v_pos < i32(V), x, _NEG_INF)       # mask the padded tail

    # flash-style online logsumexp over the vocab sweep
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(x, axis=-1, keepdims=True))
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    alpha = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_safe))
    l_new = l_scr[:, :1] * alpha + jnp.sum(jnp.exp(x - m_safe), axis=-1,
                                           keepdims=True)
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    # the label logit lives in exactly one vocab block — accumulate it
    lab = lab_ref[...]                               # (bm, 1) i32
    hit = jnp.sum(jnp.where(v_pos == lab, x, 0.0), axis=-1, keepdims=True)
    acc_scr[...] = acc_scr[...] + jnp.broadcast_to(hit, acc_scr.shape)

    @pl.when(vi == nv - 1)
    def _fin():
        l = l_scr[:, :1]
        lse = m_scr[:, :1] + jnp.log(l)
        lse_ref[...] = lse
        loss_ref[...] = lse - acc_scr[:, :1]


def _sxent_pallas(logits, labels, block_m, block_v):
    """2-D [M, V] impl; labels [M] i32.  Returns (loss [M], lse [M]) f32."""
    M, V = logits.shape
    bm = min(block_m, max(M, 8))
    bm = -(-bm // 8) * 8
    bv = min(block_v, max(V, 128))
    bv = -(-bv // 128) * 128
    Mp = -(-M // bm) * bm
    Vp = -(-V // bv) * bv
    xp = logits
    if (Mp, Vp) != (M, V):
        xp = jnp.pad(logits, ((0, Mp - M), (0, Vp - V)))
    lab = labels.reshape(M, 1)
    if Mp != M:
        lab = jnp.pad(lab, ((0, Mp - M), (0, 0)))

    interpret = jax.default_backend() != "tpu"
    row = lambda i, j: (i, 0)  # noqa: E731
    loss, lse = pl.pallas_call(
        functools.partial(_kernel, V=V, block_v=bv),
        interpret=interpret,
        grid=(Mp // bm, Vp // bv),  # vocab minor: sequential online sweep
        in_specs=[
            pl.BlockSpec((bm, bv), lambda i, j: (i, j)),
            pl.BlockSpec((bm, 1), row),
        ],
        out_specs=[
            pl.BlockSpec((bm, 1), row),
            pl.BlockSpec((bm, 1), row),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Mp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Mp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bm, 128), jnp.float32),  # running max
            pltpu.VMEM((bm, 128), jnp.float32),  # running sum-of-exp
            pltpu.VMEM((bm, 128), jnp.float32),  # label-logit accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(xp, lab)
    return loss[:M, 0], lse[:M, 0]


def _space(logits, labels, **_):
    M, V = logits.shape
    itemsize = np.dtype(logits.dtype).itemsize
    out = []
    for bm in _at.tile_candidates(M, base=(64, 128, 256, 512)):
        for bv in _at.tile_candidates(V, multiple=_at.LANE,
                                      base=(512, 1024, 2048, 4096, 8192)):
            # resident: the logits block (input dtype + f32 working copy)
            # plus the three (bm, 128) stat scratches
            resident = bm * bv * (itemsize + 4) + 3 * bm * 128 * 4
            if _at.vmem_fits(resident):
                out.append({"block_m": bm, "block_v": bv})
    return out


@_at.autotune("softmax_xent", params=("block_m", "block_v"), space=_space,
              heuristic=lambda *a, **k: {"block_m": 256, "block_v": 2048})
def _sxent_measured(logits, labels, *, block_m, block_v):
    return _sxent_pallas(logits, labels, block_m, block_v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _sxent(logits, labels, block_m, block_v):
    loss, _ = _sxent_pallas(logits, labels, block_m, block_v)
    return loss


def _sxent_fwd(logits, labels, block_m, block_v):
    loss, lse = _sxent_pallas(logits, labels, block_m, block_v)
    return loss, (logits, labels, lse)


def _sxent_bwd(block_m, block_v, res, g):
    logits, labels, lse = res
    V = logits.shape[-1]
    # d logits = (softmax(logits) - onehot) * g — one fused elementwise
    # pass; the exp never exists separately from the cotangent output
    p = jnp.exp(logits.astype(jnp.float32) - lse[:, None])
    onehot = (labels[:, None] == jnp.arange(V, dtype=labels.dtype)[None, :])
    dlogits = (p - onehot.astype(jnp.float32)) * g[:, None].astype(
        jnp.float32)
    return (dlogits.astype(logits.dtype),
            np.zeros(labels.shape, jax.dtypes.float0))


_sxent.defvjp(_sxent_fwd, _sxent_bwd)


def softmax_cross_entropy(logits, labels, *, block_m: Optional[int] = None,
                          block_v: Optional[int] = None):
    """Per-row ``-log_softmax(logits)[label]`` without materializing the
    probability (or log-probability) matrix in the forward.

    logits: ``[..., V]``, labels: ``[...]`` integer class ids in
    ``[0, V)``.  Returns float32 losses of the label shape.  Blocks
    default to the autotuner.  Differentiable in logits; labels get a
    symbolic-zero cotangent.
    """
    logits = jnp.asarray(logits)
    labels = jnp.asarray(labels)
    if logits.shape[:-1] != labels.shape:
        raise InvalidArgumentError(
            f"softmax_cross_entropy: logits {logits.shape} vs labels "
            f"{labels.shape}")
    V = logits.shape[-1]
    lead = labels.shape
    x2 = logits.reshape(-1, V)
    lab2 = labels.reshape(-1).astype(jnp.int32)
    if block_m is None or block_v is None:
        cfg = _sxent_measured.config(x2, lab2)
        block_m = cfg["block_m"] if block_m is None else block_m
        block_v = cfg["block_v"] if block_v is None else block_v
    loss = _sxent(x2, lab2, int(block_m), int(block_v))
    return loss.reshape(lead)

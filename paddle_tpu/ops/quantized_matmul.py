"""Quantized linear kernels for the serving path — Pallas TPU.

The serving quantization stack (``GPTConfig.quantization`` /
``GenerationEngine(quantized=...)``) stores parallel-linear weights as
int8 or fp8-e4m3 plus a per-output-channel float32 dequant multiplier
(``weight_scale``, see ``slim.quantize_weights``).  This module is the
compute half: activations are quantized on the fly (per-tensor abs-max,
the LLM.int8() absmax recipe without the outlier split — serving-scale
models here stay within int8 range), the matmul runs on low-precision
operands, and ONE fused epilogue applies the combined
``weight_scale * act_scale`` rescale plus the bias:

    int8:  acc = x_q  @ w_q   (int8 × int8 → int32 on the MXU)
    fp8:   acc = x_q  @ w_q   (e4m3 operands, f32 accumulate)
    out    = acc * (weight_scale * act_scale) + bias

Tile sizes come from ``ops.autotune`` (kernel name "quantized_matmul");
the cache key carries each operand's dtype, so one registration covers
the int8 and fp8 legs with independent tunings.  int8/fp8 arrays tile
as (32, 128) on Mosaic — row blocks are multiples of 32, column blocks
of 128, and the whole contraction dim rides in VMEM zero-padded to a
lane multiple (exact: padded products are zero).

Off-TPU (and under model/sep sharding — ``pallas_call`` has no GSPMD
partitioning rule) the same math runs as a plain XLA ``dot_general``
with the identical quantize → accumulate → rescale structure, so tokens
do not depend on which backend executed the layer.  Inference only: no
VJP is defined.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # jax < 0.6 spells it TPUCompilerParams
    pltpu.CompilerParams = pltpu.TPUCompilerParams

from ..framework.errors import InvalidArgumentError
from . import autotune as _at

__all__ = ["quantized_matmul", "fp8_matmul", "quantized_linear",
           "quantize_activations"]

#: largest finite float8_e4m3fn (no inf in e4m3fn — clip before casting)
_FP8_MAX = 448.0

#: Mosaic sublane tile for 8-bit operand arrays
_SUBLANE_8BIT = 32


def _kernel(x_ref, w_ref, s_ref, b_ref, o_ref):
    if x_ref.dtype == jnp.int8:
        acc = jnp.dot(x_ref[...], w_ref[...],
                      preferred_element_type=jnp.int32).astype(jnp.float32)
    else:
        # e4m3 operands: accumulate in f32 (upcast keeps the interpret
        # backend and older TPU generations on the same numerics)
        acc = jnp.dot(x_ref[...].astype(jnp.float32),
                      w_ref[...].astype(jnp.float32),
                      preferred_element_type=jnp.float32)
    o_ref[...] = acc * s_ref[0] + b_ref[0]


def _qmm_pallas(xq, wq, scale, bias, block_m, block_n):
    """[M, K]q @ [K, N]q with the dequant+bias epilogue fused; returns
    float32 [M, N].  ``scale`` / ``bias`` are [N] float32 (the scale
    already folds the activation scale in)."""
    M, K = xq.shape
    N = wq.shape[1]
    bm = min(block_m, max(M, _SUBLANE_8BIT))
    bm = -(-bm // _SUBLANE_8BIT) * _SUBLANE_8BIT
    bn = min(block_n, max(N, 128))
    bn = -(-bn // 128) * 128
    Mp = -(-M // bm) * bm
    Np = -(-N // bn) * bn
    Kp = -(-K // 128) * 128
    if Mp != M or Kp != K:
        xq = jnp.pad(xq, ((0, Mp - M), (0, Kp - K)))
    if Kp != K or Np != N:
        wq = jnp.pad(wq, ((0, Kp - K), (0, Np - N)))
    if Np != N:
        scale = jnp.pad(scale, (0, Np - N))
        bias = jnp.pad(bias, (0, Np - N))
    s2 = scale.reshape(1, Np).astype(jnp.float32)
    b2 = bias.reshape(1, Np).astype(jnp.float32)

    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        _kernel,
        interpret=interpret,
        grid=(Mp // bm, Np // bn),
        in_specs=[
            pl.BlockSpec((bm, Kp), lambda i, j: (i, 0)),
            pl.BlockSpec((Kp, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, Np), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(xq, wq, s2, b2)
    return out[:M, :N]


def _space(xq, wq, scale, bias, **_):
    M, K = xq.shape
    N = wq.shape[1]
    Kp = -(-K // 128) * 128
    item = np.dtype(xq.dtype).itemsize  # 1 for int8 and e4m3
    out = []
    for bm in _at.tile_candidates(M, multiple=_SUBLANE_8BIT,
                                  base=(64, 128, 256, 512)):
        for bn in _at.tile_candidates(N, multiple=_at.LANE,
                                      base=(128, 256, 512)):
            # resident: x row block + w col block (whole K), scale/bias
            # rows, f32 accumulator/out block
            resident = ((bm * Kp + Kp * bn) * item + 2 * bn * 4
                        + bm * bn * 4)
            if _at.vmem_fits(resident):
                out.append({"block_m": bm, "block_n": bn})
    return out


@_at.autotune("quantized_matmul", params=("block_m", "block_n"),
              space=_space,
              heuristic=lambda *a, **k: {"block_m": 128, "block_n": 128})
def _qmm_measured(xq, wq, scale, bias, *, block_m, block_n):
    return _qmm_pallas(xq, wq, scale, bias, block_m, block_n)


def quantize_activations(x, mode: str):
    """Dynamic per-tensor activation quantization: float [..., K] →
    (quantized x, scalar float32 dequant multiplier)."""
    xf = jnp.asarray(x, jnp.float32)
    amax = jnp.maximum(jnp.max(jnp.abs(xf)), 1e-9)
    if mode == "int8":
        xq = jnp.clip(jnp.round(xf * (127.0 / amax)),
                      -127, 127).astype(jnp.int8)
        return xq, amax / 127.0
    if mode == "fp8":
        xq = jnp.clip(xf * (_FP8_MAX / amax),
                      -_FP8_MAX, _FP8_MAX).astype(jnp.float8_e4m3fn)
        return xq, amax / _FP8_MAX
    raise InvalidArgumentError(
        f"quantization mode must be 'int8' or 'fp8', got {mode!r}")


def _use_pallas(n_features: int) -> bool:
    # same gate as the other fused epilogues: real TPU, lane-aligned
    # output features, no model/sep sharding (pallas_call cannot be
    # GSPMD-partitioned).  Interpret-mode pallas would only slow the
    # CPU test path down; the XLA fallback is numerically identical.
    return _at.fused_epilogues_eligible(feature_dim=n_features)


def quantized_linear(x, w_q, weight_scale, bias=None):
    """The serving Linear hot path: float activations × pre-quantized
    weights, dispatched on the weight dtype.

    ``x`` float ``[..., K]``; ``w_q`` int8 or float8_e4m3fn ``[K, N]``;
    ``weight_scale`` float32 ``[N]`` per-channel dequant multiplier
    (``w ≈ w_q * weight_scale``, the ``slim.quantize_weights``
    convention); optional ``bias`` ``[N]``.  Activations are quantized
    on the fly per-tensor; output returns in ``x.dtype``."""
    x = jnp.asarray(x)
    w_q = jnp.asarray(w_q)
    if w_q.dtype == jnp.int8:
        mode = "int8"
    elif w_q.dtype == jnp.float8_e4m3fn:
        mode = "fp8"
    else:
        raise InvalidArgumentError(
            f"quantized_linear: weight dtype {w_q.dtype} is not int8 or "
            f"float8_e4m3fn")
    if weight_scale is None:
        raise InvalidArgumentError(
            "quantized_linear: quantized weights need a weight_scale "
            "(per-output-channel float32 dequant multiplier)")
    K, N = w_q.shape
    lead = x.shape[:-1]
    xq, act_scale = quantize_activations(x, mode)
    x2 = xq.reshape(-1, K)
    combined = (jnp.asarray(weight_scale, jnp.float32).reshape(-1)
                * act_scale)
    b = (jnp.zeros((N,), jnp.float32) if bias is None
         else jnp.asarray(bias, jnp.float32).reshape(-1))
    if _use_pallas(N):
        out2 = _qmm_measured(x2, w_q, combined, b)
    else:
        if mode == "int8":
            acc = jax.lax.dot_general(
                x2, w_q, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32).astype(jnp.float32)
        else:
            acc = jnp.dot(x2.astype(jnp.float32),
                          w_q.astype(jnp.float32))
        out2 = acc * combined[None, :] + b[None, :]
    out_dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.float32
    return out2.reshape(*lead, N).astype(out_dtype)


def quantized_matmul(x, w_q, weight_scale, bias=None):
    """int8 leg of :func:`quantized_linear` (validates the dtype)."""
    w_q = jnp.asarray(w_q)
    if w_q.dtype != jnp.int8:
        raise InvalidArgumentError(
            f"quantized_matmul: weight dtype {w_q.dtype} is not int8")
    return quantized_linear(x, w_q, weight_scale, bias)


def fp8_matmul(x, w_q, weight_scale, bias=None):
    """fp8-e4m3 leg of :func:`quantized_linear` (validates the dtype)."""
    w_q = jnp.asarray(w_q)
    if w_q.dtype != jnp.float8_e4m3fn:
        raise InvalidArgumentError(
            f"fp8_matmul: weight dtype {w_q.dtype} is not float8_e4m3fn")
    return quantized_linear(x, w_q, weight_scale, bias)

"""Paged flash-decode attention — the page-table walk INSIDE the kernel.

The paged serving decode path (``GPTModel.forward_paged``) historically
attended over a gathered KV view: ``jnp.take`` materializes each slot's
logical ``[B, H, C, hd]`` cache from the shared page pool, quantized
pages are dequantized to float IN FULL before attention, and a plain
einsum runs over the result — one full HBM round-trip of the decode
working set per layer per step, twice that for quantized pools (read
int8, write float, read float).  PagedAttention (Kwon SOSP'23) puts the
page-table indirection inside the attention kernel instead; this module
is that kernel for TPU, in the shape of the repo's other Pallas kernels:

* grid ``(B, H/bh, G)`` with the page dim innermost/sequential — each
  grid step streams ONE physical page of K/V for ``bh`` heads straight
  from the pool into VMEM, located by a scalar-prefetched i32 page
  table (``PrefetchScalarGridSpec`` — index maps stay SMEM lookups,
  which Mosaic lowers directly; the splash-attention pattern shared
  with flash_attention.py's triangle grid);
* flash-style online softmax: running max / normalizer / output
  accumulator ride VMEM scratch across the sequential page sweep, so
  attention memory is O(page), never O(C);
* quantized pools (int8 / fp8-e4m3) dequantize PER PAGE inside the
  inner loop — ``k_f32 = k_q * k_scale`` on the [page, hd] block that
  is already in VMEM.  A float KV view is never materialized in HBM;
  the pool bytes crossing the memory bus per step are the quantized
  bytes (the whole point of a quantized pool);
* masking is the host-computed validity mask the gather path already
  uses (causality, ragged page counts, the write-drop page, and the
  speculative ``1+k`` verify width all fold into it — the pool is
  scattered BEFORE attention, so intra-step draft causality is just
  ``kp <= qp``).  Unmapped table entries are pre-clipped to page 0 and
  carry mask 0; fully-masked rows (query padding) emit zeros.

Equivalence: same math as the gather-then-attend reference modulo
float reassociation (online softmax accumulates in f32); the reference
path stays the bit-identical CPU/fallback — ``paged_flash_eligible``
gates dispatch exactly like ``fused_epilogues_eligible`` does for the
other epilogues (TPU backend, no model/sep sharding, aligned dims).

Tile parameters resolve through ``ops.autotune`` (kernel name
``"paged_decode"``): ``block_h`` — heads per grid step — trades grid
overhead against VMEM residency; candidates are the divisors of H
that fit the VMEM budget, per-candidate equivalence is tested in
tests/test_paged_attention.py.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # jax < 0.6 spells it TPUCompilerParams
    pltpu.CompilerParams = pltpu.TPUCompilerParams

from ..framework.errors import InvalidArgumentError
from ..framework.flags import flag
from . import autotune as _at

__all__ = ["paged_flash_decode", "paged_flash_eligible"]

_NEG = -1e30  # mask fill; exp(_NEG - m) underflows to exactly 0.0 in f32


def _kernel(tab_ref, q_ref, k_ref, v_ref, mask_ref, *refs,
            block_h: int, sm_scale: float, quantized: bool):
    """One (slot, head-block, page) step of the online-softmax sweep."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_s, l_s, acc_s = refs
    else:
        ks_ref = vs_ref = None
        o_ref, m_s, l_s, acc_s = refs
    g = pl.program_id(2)
    g_steps = pl.num_programs(2)

    @pl.when(g == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, _NEG)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    mask = mask_ref[0, :, 0, :]  # [Tp, page] 0/1 f32
    for h in range(block_h):  # static unroll: 2-D MXU dots per head
        q = q_ref[0, h].astype(jnp.float32)   # [Tp, hd]
        k = k_ref[0, h].astype(jnp.float32)   # [page, hd]
        v = v_ref[0, h].astype(jnp.float32)
        if quantized:
            # fused dequant: one multiplier per (page entry, head),
            # applied to the block already resident in VMEM — the f32
            # K/V never exists outside this register window
            k = k * ks_ref[0, h][:, None]
            v = v * vs_ref[0, h][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [Tp, page]
        s = jnp.where(mask > 0, s, _NEG)

        m_prev = m_s[h]                       # [Tp, LANE], lanes equal
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)       # [Tp, LANE]
        p = jnp.exp(s - m_new[:, :1]) * mask  # masked/padded entries -> 0
        l_s[h] = l_s[h] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_s[h] = (acc_s[h] * alpha[:, :1]
                    + jax.lax.dot_general(
                        p, v, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32))
        m_s[h] = m_new

    @pl.when(g == g_steps - 1)
    def _flush():
        for h in range(block_h):
            l = l_s[h][:, :1]  # fully-masked rows (query padding): l == 0
            out = jnp.where(l > 0, acc_s[h] / jnp.maximum(l, 1e-30), 0.0)
            o_ref[0, h] = out.astype(o_ref.dtype)


def _space(q, k_pool, v_pool, tables, mask, k_scale, v_scale):
    """Candidate head-block sizes: divisors of H whose resident blocks
    (q/k/v/mask/scale blocks + the three scratch accumulators) fit the
    VMEM budget."""
    B, H, T, hd = q.shape
    page = k_pool.shape[2]
    Tp = -(-T // _at.SUBLANE) * _at.SUBLANE
    kv_item = np.dtype(k_pool.dtype).itemsize
    q_item = np.dtype(q.dtype).itemsize
    out = []
    for bh in (1, 2, 4, 8, 16):
        if bh > H or H % bh:
            continue
        resident = (bh * Tp * hd * (q_item + 4)      # q block + out block
                    + 2 * bh * page * hd * kv_item   # k/v page blocks
                    + Tp * page * 4                  # mask block
                    + bh * Tp * (2 * _at.LANE + hd) * 4)  # m/l/acc scratch
        if k_scale is not None:
            resident += 2 * bh * page * 4
        if _at.vmem_fits(resident):
            out.append({"block_h": bh})
    return out


def _heuristic(q, k_pool, v_pool, tables, mask, k_scale, v_scale):
    # one head per grid step — the smallest block is always lowerable
    # and is the pre-autotuner default every backend agrees on
    return {"block_h": 1}


@_at.autotune("paged_decode", params=("block_h",), space=_space,
              heuristic=_heuristic)
@functools.partial(jax.jit, static_argnames=("block_h",))
def _paged_decode(q, k_pool, v_pool, tables, mask, k_scale, v_scale, *,
                  block_h: int):
    B, H, T, hd = q.shape
    P1, Hk, page, hdk = k_pool.shape
    G = tables.shape[1]
    if (Hk, hdk) != (H, hd) or v_pool.shape != k_pool.shape:
        raise InvalidArgumentError(
            f"paged_flash_decode: pool {k_pool.shape}/{v_pool.shape} vs "
            f"q {q.shape}")
    if mask.shape != (B, T, G * page):
        raise InvalidArgumentError(
            f"paged_flash_decode: mask {mask.shape} != {(B, T, G * page)}")
    bh = block_h if H % block_h == 0 else 1
    quantized = k_scale is not None
    sm_scale = 1.0 / math.sqrt(hd)

    # pad the verify width to the sublane tile; padded rows carry mask 0
    # everywhere, so they finalize to zeros and are sliced away below
    Tp = -(-T // _at.SUBLANE) * _at.SUBLANE
    qp = q if Tp == T else jnp.pad(q, ((0, 0), (0, 0), (0, Tp - T), (0, 0)))
    maskf = mask.astype(jnp.float32)
    if Tp != T:
        maskf = jnp.pad(maskf, ((0, 0), (0, Tp - T), (0, 0)))
    maskf = maskf.reshape(B, Tp, G, page)
    tab = tables.astype(jnp.int32)  # [B, G] SMEM table for the index maps

    def qmap(b, h, g, t):
        return (b, h, 0, 0)

    def kvmap(b, h, g, t):
        return (t[b, g], h, 0, 0)

    def scmap(b, h, g, t):
        return (t[b, g], h, 0)

    def mmap(b, h, g, t):
        return (b, 0, g, 0)

    in_specs = [
        pl.BlockSpec((1, bh, Tp, hd), qmap),
        pl.BlockSpec((1, bh, page, hd), kvmap),
        pl.BlockSpec((1, bh, page, hd), kvmap),
        pl.BlockSpec((1, Tp, 1, page), mmap),
    ]
    operands = [qp, k_pool, v_pool, maskf]
    if quantized:
        in_specs += [pl.BlockSpec((1, bh, page), scmap),
                     pl.BlockSpec((1, bh, page), scmap)]
        operands += [k_scale, v_scale]

    kern = functools.partial(_kernel, block_h=bh, sm_scale=sm_scale,
                             quantized=quantized)
    out = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H // bh, G),  # page dim innermost: sequential sweep
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, bh, Tp, hd), qmap),
            scratch_shapes=[
                pltpu.VMEM((bh, Tp, _at.LANE), jnp.float32),  # running max
                pltpu.VMEM((bh, Tp, _at.LANE), jnp.float32),  # running sum
                pltpu.VMEM((bh, Tp, hd), jnp.float32),        # out accum
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, Tp, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=jax.default_backend() != "tpu",
    )(tab, *operands)
    return out[:, :, :T, :]


def paged_flash_decode(q, k_pool, v_pool, tables, mask,
                       k_scale=None, v_scale=None, *,
                       block_h: Optional[int] = None):
    """Flash decode over a paged KV pool, page walk in-kernel.

    q: ``[B, H, T, hd]`` query block (T = 1 or the speculative ``1+k``
    verify width); k_pool/v_pool: ``[P+1, H, page, hd]`` shared page
    pools (float, int8 or fp8-e4m3; the last page is the write-drop
    page), ALREADY scattered with this step's K/V; tables: ``[B, G]``
    i32 page-table rows with unmapped entries pre-clipped to a valid
    page (``jnp.maximum(table, 0)`` — their mask is 0); mask:
    ``[B, T, G*page]`` bool validity, identical to the gather path's;
    k_scale/v_scale: ``[P+1, H, page]`` f32 dequant multipliers for
    quantized pools (both or neither).

    Returns the attention context ``[B, H, T, hd]`` in q's dtype.
    ``block_h`` defaults to the autotuner; pass it explicitly to bypass
    tuning.
    """
    if (k_scale is None) != (v_scale is None):
        raise InvalidArgumentError(
            "paged_flash_decode: pass k_scale and v_scale together "
            "(or neither)")
    return _paged_decode(q, k_pool, v_pool, tables, mask, k_scale, v_scale,
                         block_h=block_h)


def paged_flash_eligible(head_dim: Optional[int] = None,
                         page_size: Optional[int] = None,
                         backend: Optional[str] = None) -> bool:
    """Should ``forward_paged`` dispatch to the Pallas kernel?  Mirrors
    ``fused_epilogues_eligible``: a real TPU backend (interpret mode
    loses; the gather path is the bit-identical CPU reference), Mosaic-
    friendly head/page dims, and no model/sep sharding — ``pallas_call``
    has no GSPMD partitioning rule.  ``backend`` overrides the backend
    check so CI on CPU can assert the would-dispatch-on-TPU decision
    (tools/gen_smoke.py / quant_smoke.py)."""
    if not flag("paged_flash"):
        return False
    if (backend or jax.default_backend()) != "tpu":
        return False
    if head_dim is not None and head_dim % _at.SUBLANE != 0:
        return False
    if page_size is not None and page_size % _at.SUBLANE != 0:
        return False
    from ..distributed.mesh import get_mesh

    mesh = get_mesh()
    return (mesh.shape.get("model", 1) == 1
            and mesh.shape.get("sep", 1) == 1)

"""InMemoryDataset — file-sharded native ingest with global shuffle.

Parity: the reference's dataset-driven ingest path (paddle.distributed.
InMemoryDataset over framework/data_set.h:157 + InMemoryDataFeed
data_feed.h:302): C++ reader threads parse a file list straight into an
in-memory store, the store is globally shuffled, and minibatches are
assembled natively — Python never touches individual samples.  The C++
engine lives in paddle_tpu/native/ingest.cc (ctypes ABI).

Differences by design: one controller per host (not one feed per device
worker) — the assembled global batch is split across chips by the normal
sharding plan; ragged LoD slots become fixed-width columns (pad/bucket
upstream — XLA wants static shapes).

Usage::

    ds = InMemoryDataset(slots=[("dense", 13, "float32"),
                                ("label", 1, "int64")])
    ds.set_filelist(["part-0.txt", "part-1.txt"])   # numeric text columns
    ds.load_into_memory(thread_num=8)
    ds.global_shuffle(seed=7)
    for dense, label in ds.batch_iter(batch_size=256):
        model.train_batch([dense], [label])
"""
from __future__ import annotations

import ctypes
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..framework.errors import InvalidArgumentError, NotFoundError

__all__ = ["InMemoryDataset", "MultiSlotInMemoryDataset"]


class _IngestStoreBase:
    """Shared surface over one native ingest store handle: filelist,
    threaded load (FLAGS_paddle_num_threads default), shuffle, size,
    release.  Subclasses own handle creation and batch assembly."""

    def set_filelist(self, files: Sequence[str]):
        self._filelist = [str(f) for f in files]

    def load_into_memory(self, thread_num: Optional[int] = None) -> int:
        """Parse the filelist with ``thread_num`` native readers (default:
        FLAGS_paddle_num_threads); returns samples added.  Raises with
        file:line context on malformed input."""
        from ..framework import monitor as _monitor
        from ..framework.flags import flag as _flag

        if thread_num is None:
            thread_num = max(int(_flag("paddle_num_threads")), 1)
        if not self._filelist:
            raise InvalidArgumentError("set_filelist() first")
        arr = (ctypes.c_char_p * len(self._filelist))(
            *[f.encode() for f in self._filelist])
        n = self._lib.ingest_load(self._h, arr, len(self._filelist),
                                  int(thread_num))
        if n < 0:
            msg = self._lib.ingest_error(self._h).decode()
            exc = NotFoundError if "cannot open" in msg else InvalidArgumentError
            raise exc(f"load_into_memory: {msg}")
        _monitor.stat_add("ingest_samples", int(n))
        return int(n)

    def global_shuffle(self, seed: int = 0):
        """Shuffle the whole store (single controller — the reference's
        cross-node exchange reduces to one permutation here)."""
        self._lib.ingest_shuffle(self._h, int(seed) & (2**64 - 1))

    local_shuffle = global_shuffle  # one store per host

    def get_memory_data_size(self) -> int:
        return int(self._lib.ingest_size(self._h))

    def release_memory(self):
        self._lib.ingest_clear(self._h)
        self._filelist = []

    def __len__(self) -> int:
        return self.get_memory_data_size()

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None):
            self._lib.ingest_destroy(h)

    def __iter__(self):
        raise InvalidArgumentError(
            "iterate with batch_iter(batch_size=...) — sample-wise Python "
            "iteration would defeat the native batch path")


class InMemoryDataset(_IngestStoreBase):
    """``slots``: ordered (name, width, dtype) column groups; every input
    line must hold exactly ``sum(width)`` numeric fields."""

    def __init__(self, slots: Sequence[Tuple[str, int, str]]):
        from ..native import ingest_lib

        if not slots:
            raise InvalidArgumentError("need at least one slot")
        self._slots = [(str(n), int(w), np.dtype(d)) for n, w, d in slots]
        for n, w, _ in self._slots:
            if w <= 0:
                raise InvalidArgumentError(f"slot {n!r} width must be > 0")
        self._ncols = sum(w for _, w, _ in self._slots)
        self._lib = ingest_lib()
        self._h = self._lib.ingest_create(self._ncols)
        if not self._h:
            raise MemoryError("ingest_create failed")
        self._filelist: List[str] = []

    def batch_iter(self, batch_size: int, drop_last: bool = False
                   ) -> Iterator[Tuple[np.ndarray, ...]]:
        """Assemble minibatches natively; yields one ndarray per slot
        (shape [b, width], slot dtype).  Each call starts an independent
        epoch over the current permutation — iterators own their cursor,
        so nested/concurrent iteration is safe."""
        if batch_size <= 0:
            raise InvalidArgumentError("batch_size must be > 0")
        return self._batch_gen(int(batch_size), bool(drop_last))

    def _batch_gen(self, batch_size, drop_last):
        buf = np.empty((batch_size, self._ncols), np.float64)
        ptr = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        pos = 0
        while True:
            got = self._lib.ingest_copy_rows(self._h, ptr, pos, batch_size)
            if got <= 0:
                return
            pos += got
            if got < batch_size and drop_last:
                return
            rows = buf[:got]
            out = []
            col = 0
            for _, w, dt in self._slots:
                out.append(np.ascontiguousarray(rows[:, col:col + w]).astype(dt))
                col += w
            yield tuple(out)


class MultiSlotInMemoryDataset(_IngestStoreBase):
    """Typed multi-slot ingest over the reference's MultiSlot text format
    (data_feed.h:302 MultiSlotDataFeed): each line holds, per declared
    slot, ``<count> v1 ... vcount`` — exactly what
    :mod:`paddle_tpu.distributed.fleet.data_generator` emits.

    ``slots``: ordered ``(name, dtype, max_len)`` declarations with dtype
    ``"float32"`` or ``"int64"``.  Variable-length slots come back as
    ``(values [b, max_len] padded with zeros, lens [b] int64)``; slots
    with ``max_len == 1`` yield just ``values [b, 1]`` (the common dense
    feature / label case).

    The parse/shuffle/batch path is the same C++ engine as
    :class:`InMemoryDataset` — values are stored in their declared dtype
    (int64 ids are exact at full width, unlike the dense f64 store).
    """

    _TYPE_TAGS = {"float32": 0, "int64": 1}

    def __init__(self, slots):
        from ..native import ingest_lib

        if not slots:
            raise InvalidArgumentError("need at least one slot")
        self._slots = []
        for n, dt, ml in slots:
            if dt not in self._TYPE_TAGS:
                raise InvalidArgumentError(
                    f"slot {n!r} dtype must be float32/int64, got {dt!r}")
            if int(ml) <= 0:
                raise InvalidArgumentError(f"slot {n!r} max_len must be > 0")
            self._slots.append((str(n), str(dt), int(ml)))
        self._lib = ingest_lib()
        n = len(self._slots)
        types = (ctypes.c_int64 * n)(*[self._TYPE_TAGS[d]
                                       for _, d, _ in self._slots])
        lens = (ctypes.c_int64 * n)(*[ml for _, _, ml in self._slots])
        self._h = self._lib.ingest_create_multislot(n, types, lens)
        if not self._h:
            raise MemoryError("ingest_create_multislot failed")
        self._filelist: List[str] = []

    def batch_iter(self, batch_size: int, drop_last: bool = False,
                   return_lens: bool = False):
        """Yields a tuple with one ``values`` array per slot, or
        ``(values, lens)`` pairs when ``return_lens`` is set."""
        if batch_size <= 0:
            raise InvalidArgumentError("batch_size must be > 0")
        return self._batch_gen(int(batch_size), bool(drop_last),
                               bool(return_lens))

    def _batch_gen(self, batch_size, drop_last, return_lens):
        np_dt = {"float32": np.float32, "int64": np.int64}
        bufs = [np.empty((batch_size, ml), np_dt[dt])
                for _, dt, ml in self._slots]
        lbufs = [np.empty((batch_size,), np.int64) for _ in self._slots]
        pos = 0
        while True:
            got = None
            for si in range(len(self._slots)):
                g = self._lib.ingest_copy_slot(
                    self._h, si, pos, batch_size,
                    bufs[si].ctypes.data_as(ctypes.c_void_p),
                    lbufs[si].ctypes.data_as(
                        ctypes.POINTER(ctypes.c_int64)))
                if got is None:
                    got = g
                elif g != got:  # engine invariant: slots advance together
                    raise InvalidArgumentError(
                        "slot row counts diverged (corrupt store)")
            if not got:
                return
            pos += got
            if got < batch_size and drop_last:
                return
            out = []
            for si in range(len(self._slots)):
                vals = bufs[si][:got].copy()
                if return_lens:
                    out.append((vals, lbufs[si][:got].copy()))
                else:
                    out.append(vals)
            yield tuple(out)

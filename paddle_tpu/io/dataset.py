"""Dataset abstractions.

Parity: python/paddle/io/ (reference: python/paddle/fluid/dataloader/dataset.py
— Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
Subset, random_split).
"""
from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence

import numpy as np

from ..framework.errors import InvalidArgumentError

#: the one on-disk cache root every dataset family shares (text + vision)
import os

DEFAULT_DATA_ROOT = os.path.expanduser("~/.cache/paddle_tpu/datasets")

__all__ = [
    "Dataset",
    "IterableDataset",
    "TensorDataset",
    "ComposeDataset",
    "ChainDataset",
    "ConcatDataset",
    "Subset",
    "random_split",
]


class Dataset:
    """Map-style dataset: implement __getitem__ and __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError("'{}' must implement __getitem__".format(type(self).__name__))

    def __len__(self):
        raise NotImplementedError("'{}' must implement __len__".format(type(self).__name__))


class IterableDataset(Dataset):
    """Stream-style dataset: implement __iter__."""

    def __iter__(self):
        raise NotImplementedError("'{}' must implement __iter__".format(type(self).__name__))

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not indexable")

    def __len__(self):
        # TypeError, not RuntimeError: list()/length_hint probe __len__ and
        # only swallow TypeError for unsized objects
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    """Wrap equal-first-dim arrays; sample i is a tuple of row i of each."""

    def __init__(self, tensors: Sequence):
        arrays = [np.asarray(t) for t in tensors]
        if not arrays:
            raise InvalidArgumentError("TensorDataset needs at least one tensor")
        n = arrays[0].shape[0]
        for a in arrays:
            if a.shape[0] != n:
                raise InvalidArgumentError("all tensors must share dim 0")
        self.tensors = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    """Zip datasets of equal length; sample i concatenates their fields."""

    def __init__(self, datasets: Sequence[Dataset]):
        if not datasets:
            raise InvalidArgumentError("ComposeDataset needs datasets")
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        for d in self.datasets:
            if len(d) != n:
                raise InvalidArgumentError("all datasets must share length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (tuple, list)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    """Concatenate iterable datasets back-to-back (streaming)."""

    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    """Concatenate map-style datasets (paddle 2.x / torch semantics)."""

    def __init__(self, datasets: Iterable[Dataset]):
        self.datasets = list(datasets)
        if not self.datasets:
            raise InvalidArgumentError("ConcatDataset needs datasets")
        self.cumulative_sizes: List[int] = []
        s = 0
        for d in self.datasets:
            s += len(d)
            self.cumulative_sizes.append(s)

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence[int], generator=None):
    """Split into non-overlapping subsets of the given lengths."""
    if sum(lengths) != len(dataset):
        raise InvalidArgumentError(
            f"sum of lengths {sum(lengths)} != dataset size {len(dataset)}"
        )
    from ..framework import random as _random
    import jax

    key = (generator.next_key() if generator is not None
           else _random.default_generator().next_key())
    perm = np.asarray(jax.random.permutation(key, len(dataset)))
    out = []
    offset = 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out

"""DataLoader — multiprocess host input pipeline with device double-buffering.

Parity: paddle.io.DataLoader (reference: python/paddle/fluid/reader.py:147 —
multiprocess workers over a shared-memory queue; C++ side
operators/reader/buffered_reader.cc — double-buffered async H2D staging).

TPU-native design:

* worker pool (forked processes, dataset shipped once per worker via the
  pool initializer — the reference ships samples back over a shared-memory
  LoDTensorBlockingQueue; we rely on pickle over pipes, which measures
  within noise for batched numpy) fetches + collates batches ahead of the
  consumer, ``prefetch_factor`` deep;
* a staging thread ``jax.device_put``s the *next* batch while the current
  one is being consumed (the buffered_reader double-buffer, but the
  "stream" is XLA's async dispatch);
* batches arrive as committed device arrays ready to feed a jitted step —
  by the time step N's compute finishes, batch N+1's H2D copy has overlapped
  with it.

``return_numpy=True`` skips staging (for hosts that feed a sharded
device_put themselves, e.g. the fleet data-parallel path).
"""
from __future__ import annotations

import itertools
import multiprocessing
import queue
import threading
import time
from concurrent.futures import ProcessPoolExecutor
from typing import Callable, Optional

import jax
import numpy as np

from ..framework.errors import InvalidArgumentError
from ..observability import steptrace as _steptrace
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, Sampler

__all__ = ["DataLoader", "default_collate_fn", "default_convert_fn"]


def default_convert_fn(sample):
    return sample


def default_collate_fn(batch):
    """Stack a list of samples into a batch (reference:
    fluid/dataloader/collate.py default_collate_fn): arrays/numbers stack
    along a new dim 0; dict/tuple structures collate per field."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch, axis=0)
    if isinstance(sample, (int, float, np.generic)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn(list(field)) for field in zip(*batch))
    if hasattr(sample, "__array__"):  # jax arrays and friends
        return np.stack([np.asarray(s) for s in batch], axis=0)
    raise InvalidArgumentError(f"cannot collate batch of {type(sample)}")


# -- worker-process globals (set once per worker by the pool initializer) ----
_worker_dataset = None
_worker_collate = None
_worker_info = None


class WorkerInfo:
    """Worker-process identity visible to Dataset code (ref:
    fluid/dataloader/worker.py WorkerInfo / get_worker_info)."""

    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, "
                f"num_workers={self.num_workers})")


def get_worker_info():
    """Inside a DataLoader worker process: that worker's ``WorkerInfo``
    (id / num_workers / dataset); in the main process: None (ref:
    python/paddle/fluid/dataloader/worker.py get_worker_info — used by
    IterableDataset shards to split work across workers)."""
    return _worker_info


def _init_worker(dataset, collate_fn, worker_init_fn, worker_id_counter,
                 num_workers=0):
    global _worker_dataset, _worker_collate, _worker_info
    _worker_dataset = dataset
    _worker_collate = collate_fn
    with worker_id_counter.get_lock():
        worker_id = worker_id_counter.value
        worker_id_counter.value += 1
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)


def _fetch_batch(indices):
    samples = [_worker_dataset[i] for i in indices]
    return _worker_collate(samples)


class _StagingIterator:
    """Pulls collated numpy batches from ``source`` and keeps ``depth``
    batches resident on device ahead of the consumer.  ``close()`` (also
    invoked on GC) stops the producer and closes the source generator, so a
    consumer that breaks mid-epoch doesn't leak the thread or — with
    num_workers>0 — the whole worker pool."""

    _DONE = object()

    def __init__(self, source, depth: int, to_device: bool):
        self._source = source
        self._to_device = to_device
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._err: Optional[BaseException] = None
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _stage(self, batch):
        if not self._to_device:
            return batch
        from ..framework import monitor as _monitor

        for leaf in jax.tree_util.tree_leaves(batch):
            nbytes = getattr(leaf, "nbytes", 0)
            if nbytes:
                _monitor.stat_add("host_to_device_bytes", int(nbytes))
        # device_put dispatches the H2D copy asynchronously; consuming code
        # only blocks when it actually reads values.
        return jax.tree_util.tree_map(jax.device_put, batch)

    def _put(self, item) -> bool:
        while not self._stop:
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self):
        try:
            for batch in self._source:
                if not self._put(self._stage(batch)):
                    break
        except BaseException as e:  # propagate into the consumer thread
            self._err = e
        finally:
            if self._stop:
                # closing the source generator unwinds its `with pool:`
                close = getattr(self._source, "close", None)
                if close is not None:
                    close()
            self._put(self._DONE)

    def close(self):
        self._stop = True
        while True:  # drain so a blocked producer can observe _stop
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        # GC can run __del__ on any thread — including the staging thread
        # itself (join() from there raises "cannot join current thread").
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=10.0)

    def __del__(self):
        if not self._stop and self._thread.is_alive():
            self.close()

    def __iter__(self):
        return self

    def __next__(self):
        st = _steptrace._active
        if st is None:
            item = self._q.get()
        else:
            # data_wait_ms: how long the training loop blocked on the
            # input pipeline for this batch (0 when prefetch kept up)
            t0 = time.perf_counter()
            item = self._q.get()
            st.record_data_wait((time.perf_counter() - t0) * 1e3)
        if item is self._DONE:
            self._q.put(self._DONE)  # keep exhausted: further next() calls
            if self._err is not None:  # must re-raise, not block forever
                raise self._err
            raise StopIteration
        return item


class _CountingIterator:
    """Final wrapper around whatever iterator ``__iter__`` built: counts
    batches as they are DELIVERED to the consumer.  The prefetch thread
    runs ahead of the training loop, so sampler-side counters over-count —
    this is the only place the "how far did the run actually get" number
    exists, and it is what ``DataLoader.state_dict()`` snapshots for exact
    resume.  Forwards ``close()`` so breaking out mid-epoch still unwinds
    the staging thread / worker pool."""

    def __init__(self, inner, loader, base: int):
        self._inner = inner
        self._loader = loader
        loader._delivered = int(base)
        loader._exhausted = False

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self._inner)
        except StopIteration:
            self._loader._exhausted = True
            raise
        self._loader._delivered += self._loader._batch_span(batch)
        return batch

    def close(self):
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()


class DataLoader:
    """Iterate a Dataset in collated, device-staged batches.

    Accepted arguments mirror paddle.io.DataLoader (feed_list/places are
    legacy static-graph knobs, accepted and ignored).

    Exact resume: ``state_dict()`` snapshots the in-epoch position
    (batches delivered to the consumer — prefetch depth never
    over-counts) plus the batch sampler's shuffle-RNG state;
    ``set_state_dict()`` arms the next ``__iter__`` to regenerate the
    same order and skip the consumed prefix.  ``incubate.checkpoint.
    AutoCheckpoint(data_loader=...)`` captures/restores this alongside
    the model RNG so resumed runs are bit-identical to uninterrupted
    ones.
    """

    def __init__(
        self,
        dataset: Dataset,
        feed_list=None,
        places=None,
        return_list: bool = True,
        batch_sampler: Optional[BatchSampler] = None,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        num_workers: int = 0,
        use_buffer_reader: bool = True,
        prefetch_factor: int = 2,
        use_shared_memory: bool = True,
        timeout: int = 0,
        worker_init_fn: Optional[Callable] = None,
        return_numpy: bool = False,
        sampler: Optional[Sampler] = None,
        superbatch: int = 1,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(int(num_workers), 0)
        self.prefetch_factor = max(int(prefetch_factor), 1)
        self.use_buffer_reader = use_buffer_reader
        self.timeout = timeout or None
        self.worker_init_fn = worker_init_fn
        self.return_numpy = return_numpy
        # superbatch=k: yield k batches stacked along a new leading axis —
        # the feed format Executor.run_steps / StaticFunction.run_steps
        # scan over.  The stack happens before device staging, so the
        # staging thread device_puts whole superbatches while the previous
        # fused chain is still executing.
        self.superbatch = max(int(superbatch), 1)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self._delivered = 0       # sampler-batches delivered this epoch
        self._exhausted = True    # no epoch in progress yet
        self._pending: Optional[dict] = None

        if self._iterable_mode:
            if batch_sampler is not None:
                raise InvalidArgumentError("IterableDataset cannot use batch_sampler")
            if self.num_workers > 0:
                import warnings

                warnings.warn(
                    "IterableDataset streams in the main process; "
                    "num_workers is ignored", RuntimeWarning)
                self.num_workers = 0
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                raise InvalidArgumentError("batch_size or batch_sampler required")
            self.batch_sampler = BatchSampler(
                dataset=None if sampler is not None else dataset,
                sampler=sampler,
                shuffle=shuffle,
                batch_size=batch_size,
                drop_last=drop_last,
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("DataLoader over IterableDataset has no len()")
        return len(self.batch_sampler)

    # -- batch sources -------------------------------------------------------
    def _iter_sync(self):
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and self.drop_last:
                return
            yield self.collate_fn(batch)

    def _iter_workers(self):
        # one pool per epoch: keeps worker lifetime scoped to iteration,
        # mirroring the reference's per-epoch worker respawn (reader.py).
        # spawn, not fork: the parent is multithreaded the moment jax
        # initializes, and forking a threaded process can deadlock the child.
        # Consequence (same as torch on spawn platforms): dataset and
        # collate_fn must be picklable at module scope.
        ctx = multiprocessing.get_context("spawn")
        worker_id_counter = ctx.Value("i", 0)
        with ProcessPoolExecutor(
            max_workers=self.num_workers,
            mp_context=ctx,
            initializer=_init_worker,
            initargs=(self.dataset, self.collate_fn, self.worker_init_fn,
                      worker_id_counter, self.num_workers),
        ) as pool:
            window = self.num_workers * self.prefetch_factor
            batches = iter(self.batch_sampler)
            pending = []
            for indices in itertools.islice(batches, window):
                pending.append(pool.submit(_fetch_batch, indices))
            while pending:
                fut = pending.pop(0)
                nxt = next(batches, None)
                if nxt is not None:
                    pending.append(pool.submit(_fetch_batch, nxt))
                yield fut.result(timeout=self.timeout)

    def _iter_superbatch(self, source):
        """Group ``superbatch`` consecutive batches and stack each field
        along a new axis 0 — the stacked-feed format the fused multi-step
        runners (Executor.run_steps) scan over.  A trailing group with
        fewer than ``superbatch`` batches is still yielded (run_steps
        infers the chain length from the leading dim); a batch whose
        shapes differ from the group so far (e.g. a short final batch
        when drop_last=False) flushes the group first rather than failing
        the stack."""

        def stack(buf):
            return jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs], axis=0),
                *buf)

        buf, sig = [], None
        for batch in source:
            s = tuple(repr(getattr(x, "shape", type(x)))
                      for x in jax.tree_util.tree_leaves(batch))
            if buf and s != sig:
                yield stack(buf)
                buf = []
            sig = s
            buf.append(batch)
            if len(buf) == self.superbatch:
                yield stack(buf)
                buf = []
        if buf:
            yield stack(buf)

    def _batch_span(self, batch) -> int:
        """Sampler-batches a delivered item represents: 1 normally; with
        superbatch>1, the stacked group's leading dim (exact even for the
        ragged tail group)."""
        if self.superbatch <= 1:
            return 1
        leaves = jax.tree_util.tree_leaves(batch)
        if leaves and hasattr(leaves[0], "shape") and leaves[0].shape:
            return int(leaves[0].shape[0])
        return 1

    def state_dict(self) -> dict:
        """In-epoch position + shuffle-RNG snapshot for exact resume."""
        if self._iterable_mode:
            raise InvalidArgumentError(
                "DataLoader over an IterableDataset cannot snapshot its "
                "position (the stream owns its own state) — implement "
                "state capture on the dataset itself")
        out = {"delivered": int(self._delivered),
               "exhausted": bool(self._exhausted)}
        bs_state = self.batch_sampler.state_dict()
        # the sampler-side next_batch runs ahead of the consumer under
        # prefetch; the delivered count is the truthful position
        bs_state["next_batch"] = int(self._delivered)
        out["batch_sampler"] = bs_state
        return out

    def set_state_dict(self, state: dict) -> None:
        """Arm the NEXT ``__iter__`` to resume from ``state``.  A snapshot
        taken between epochs (``exhausted``) arms nothing — the next epoch
        starts fresh, exactly as the uninterrupted run would."""
        if self._iterable_mode:
            raise InvalidArgumentError(
                "DataLoader over an IterableDataset cannot restore a "
                "position snapshot")
        if state.get("exhausted", False):
            self._pending = None
            return
        self._pending = dict(state)

    def __iter__(self):
        pending, self._pending = self._pending, None
        base = 0
        if pending is not None:
            self.batch_sampler.set_state_dict(pending.get("batch_sampler", {}))
            base = int(pending.get("delivered", 0))
        if self._iterable_mode:
            source = self._iter_iterable()
        elif self.num_workers > 0:
            source = self._iter_workers()
        else:
            source = self._iter_sync()
        if self.superbatch > 1:
            source = self._iter_superbatch(source)
        if self.return_numpy:
            it = iter(source)
        elif self.use_buffer_reader:
            it = _StagingIterator(source, self.prefetch_factor, to_device=True)
        else:
            it = (jax.tree_util.tree_map(jax.device_put, b) for b in source)
        if self._iterable_mode:
            return it  # no positional state to track on a raw stream
        return _CountingIterator(it, self, base)

"""paddle_tpu.io — datasets, samplers, DataLoader (paddle.io parity).

Reference surface: python/paddle/io/__init__.py re-exporting
fluid/dataloader/* and fluid/reader.py.  See dataloader.py for the
TPU-native input-pipeline design (worker pool + device double-buffering).
"""
from .dataset import (  # noqa: F401
    Dataset,
    IterableDataset,
    TensorDataset,
    ComposeDataset,
    ChainDataset,
    ConcatDataset,
    Subset,
    random_split,
)
from .sampler import (  # noqa: F401
    Sampler,
    SequenceSampler,
    RandomSampler,
    WeightedRandomSampler,
    BatchSampler,
    DistributedBatchSampler,
)
from .in_memory_dataset import InMemoryDataset, MultiSlotInMemoryDataset  # noqa: F401
from .dataloader import (  # noqa: F401
    DataLoader,
    WorkerInfo,
    default_collate_fn,
    default_convert_fn,
    get_worker_info,
)

"""Samplers and batch samplers.

Parity: python/paddle/io/ (reference: python/paddle/fluid/dataloader/
batch_sampler.py — BatchSampler, DistributedBatchSampler:~169; sampler.py —
Sampler/SequenceSampler/RandomSampler/WeightedRandomSampler).

DistributedBatchSampler is the data-parallel shard selector: each rank reads
a disjoint 1/num_replicas slice per epoch — on TPU this pairs with a mesh
"data" axis (one process per host feeding its addressable devices).
"""
from __future__ import annotations

import math
from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..framework.errors import InvalidArgumentError

__all__ = [
    "Sampler",
    "SequenceSampler",
    "RandomSampler",
    "WeightedRandomSampler",
    "BatchSampler",
    "DistributedBatchSampler",
]


def _batched(indices, batch_size: int, drop_last: bool):
    """Group an index stream into batch lists (shared by Batch/Distributed)."""
    batch: List[int] = []
    for idx in indices:
        batch.append(idx)
        if len(batch) == batch_size:
            yield batch
            batch = []
    if batch and not drop_last:
        yield batch


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator
        # exact-resume support: the derived int seed fully determines one
        # epoch's permutation, so snapshotting it (state_dict) lets a
        # restored run regenerate the SAME order without re-consuming the
        # key source — the checkpoint's rng_state already reflects the
        # original draw.
        self._last_seed: Optional[int] = None
        self._replay_seed: Optional[int] = None
        if not replacement and num_samples is not None and num_samples > len(data_source):
            raise InvalidArgumentError("num_samples > dataset size without replacement")

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None else len(self.data_source)

    def _draw_seed(self) -> int:
        if self.generator is not None:
            next_key = getattr(self.generator, "next_key", None)
            if next_key is not None:
                # a paddle_tpu Generator: each epoch pulls a fresh key so
                # the permutation differs per epoch but replays under seed()
                key = np.asarray(next_key(), dtype=np.uint32).ravel()
                return int(key[-1]) & 0x7FFFFFFF
            # an int seed: vary per epoch deterministically
            self._epoch = getattr(self, "_epoch", -1) + 1
            return (int(self.generator) + self._epoch) & 0x7FFFFFFF
        # default: the framework generator, so paddle.seed() reproduces
        # shuffle order (consistent with random_split)
        from ..framework import random as _random

        key = np.asarray(_random.default_generator().next_key(), dtype=np.uint32).ravel()
        return int(key[-1]) & 0x7FFFFFFF

    def _rng(self):
        if self._replay_seed is not None:
            # restored state: reuse the seed that generated the epoch being
            # re-entered, WITHOUT drawing from the key source (the original
            # draw is already baked into the restored generator state)
            s, self._replay_seed = self._replay_seed, None
        else:
            s = self._draw_seed()
        self._last_seed = s
        return np.random.RandomState(s)

    def __iter__(self):
        n = len(self.data_source)
        rng = self._rng()
        if self.replacement:
            return iter(rng.randint(0, n, size=self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples

    def state_dict(self) -> dict:
        """Shuffle-RNG snapshot for exact resume (see BatchSampler)."""
        return {"last_seed": self._last_seed,
                "epoch_counter": getattr(self, "_epoch", None)}

    def set_state_dict(self, state: dict) -> None:
        self._replay_seed = state.get("last_seed")
        if state.get("epoch_counter") is not None:
            self._epoch = int(state["epoch_counter"])


class WeightedRandomSampler(Sampler):
    def __init__(self, weights: Sequence[float], num_samples: int, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, dtype=np.float64)
        if (self.weights < 0).any():
            raise InvalidArgumentError("weights must be non-negative")
        self.num_samples = num_samples
        self.replacement = replacement
        if not replacement and num_samples > len(self.weights):
            raise InvalidArgumentError("num_samples > len(weights) without replacement")

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(
            len(self.weights), size=self.num_samples, replace=self.replacement, p=p
        )
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """Group sampler indices into batches.

    Matches the reference's constructor contract: either ``dataset`` (+
    shuffle) or an explicit ``sampler``.

    Exact resume: ``state_dict()`` snapshots (next-batch index, shuffle-RNG
    seed); after ``set_state_dict()`` the NEXT ``__iter__`` regenerates the
    same index stream and skips the already-consumed batches, so a restored
    run sees the remaining batches in the original order.  ``DataLoader``
    overrides the batch index with its delivered count (prefetch makes the
    sampler-side count run ahead of the consumer)."""

    def __init__(self, dataset=None, sampler: Optional[Sampler] = None,
                 shuffle: bool = False, batch_size: int = 1, drop_last: bool = False):
        if batch_size <= 0:
            raise InvalidArgumentError("batch_size must be positive")
        if sampler is not None:
            if dataset is not None:
                raise InvalidArgumentError("give either dataset or sampler, not both")
            if shuffle:
                raise InvalidArgumentError(
                    "shuffle=True conflicts with an explicit sampler; the "
                    "sampler alone controls ordering"
                )
            self.sampler = sampler
        else:
            if dataset is None:
                raise InvalidArgumentError("need a dataset or a sampler")
            self.sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        self._yielded = 0
        self._pending: Optional[dict] = None

    def _consume_pending(self) -> int:
        """Apply restored state (if any) to the sampler; return the number
        of leading batches to skip."""
        pending, self._pending = self._pending, None
        if pending is None:
            return 0
        sampler_state = pending.get("sampler")
        if sampler_state is not None and hasattr(self.sampler, "set_state_dict"):
            self.sampler.set_state_dict(sampler_state)
        return int(pending.get("next_batch", 0))

    def __iter__(self):
        skip = self._consume_pending()
        stream = _batched(self.sampler, self.batch_size, self.drop_last)
        for _ in range(skip):
            if next(stream, None) is None:
                break
        self._yielded = skip
        for batch in stream:
            self._yielded += 1
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else (n + self.batch_size - 1) // self.batch_size

    def state_dict(self) -> dict:
        """(next-batch index, shuffle RNG) snapshot.  ``next_batch`` counts
        batches the sampler HANDED OUT — exact for synchronous iteration;
        a prefetching consumer (DataLoader) substitutes its delivered
        count."""
        out = {"next_batch": int(self._yielded)}
        sd = getattr(self.sampler, "state_dict", None)
        if sd is not None:
            out["sampler"] = sd()
        return out

    def set_state_dict(self, state: dict) -> None:
        """Arm the NEXT ``__iter__`` to replay from ``state`` (regenerate
        the permutation from the snapshotted seed, skip consumed batches)."""
        self._pending = dict(state)


class DistributedBatchSampler(BatchSampler):
    """Per-rank disjoint shard of the dataset (ref: batch_sampler.py:169).

    ``num_replicas``/``rank`` default from the distributed environment
    (paddle_tpu.distributed.ParallelEnv → jax process_index/process_count).
    ``set_epoch(e)`` reseeds the shuffle so every rank permutes identically.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        if batch_size <= 0:
            raise InvalidArgumentError("batch_size must be positive")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            # gang-aware: distributed.env covers both the jax transport
            # (process_count from the coordination service) and the file
            # gang transport, where jax sees only the local host and the
            # launch env carries rank/world
            from ..distributed import env as _denv

            num_replicas = (num_replicas if num_replicas is not None
                            else _denv.process_count())
            rank = rank if rank is not None else _denv.process_index()
        if rank >= num_replicas or rank < 0:
            raise InvalidArgumentError(f"rank {rank} out of range for {num_replicas} replicas")
        self.nranks = self.num_replicas = num_replicas
        self.local_rank = self.rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / num_replicas))
        self.total_size = self.num_samples * num_replicas
        self._yielded = 0
        self._pending: Optional[dict] = None

    def __iter__(self):
        pending, self._pending = self._pending, None
        skip = int(pending.get("next_batch", 0)) if pending else 0
        n = len(self.dataset)
        if self.shuffle:
            # the permutation is a pure function of the epoch — restoring
            # ``epoch`` (set_state_dict) regenerates it exactly
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad to make evenly divisible (reference pads by wrapping; loop so
        # datasets smaller than total_size/2 still fill up)
        while len(indices) < self.total_size:
            indices += indices[: self.total_size - len(indices)]
        local = indices[self.rank : self.total_size : self.num_replicas]
        assert len(local) == self.num_samples
        stream = _batched(local, self.batch_size, self.drop_last)
        for _ in range(skip):
            if next(stream, None) is None:
                break
        self._yielded = skip
        for batch in stream:
            self._yielded += 1
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def state_dict(self) -> dict:
        """(epoch, next-batch index) snapshot — the per-rank shard order is
        a pure function of the epoch, so this is the complete state."""
        return {"epoch": int(self.epoch), "next_batch": int(self._yielded)}

    def set_state_dict(self, state: dict) -> None:
        if "epoch" in state:
            self.epoch = int(state["epoch"])
        self._pending = {"next_batch": int(state.get("next_batch", 0))}

"""Training supervisor: divergence rollback over exact-resume checkpoints.

The serving half of the stack degrades gracefully (circuit breakers,
failover, hedging); this module is the *training-loop* dual — the
reference's launch_utils watch loop + heart_beat_monitor kept trainers
alive, but nothing guarded the run's *numerics*.  A single NaN batch
poisons every parameter within a step, and without rollback the job burns
its remaining budget training garbage.

:class:`TrainingSupervisor` wraps the epoch loop with three guarantees:

* **Divergence guard** — ``guard(loss)`` checks every step's loss (and
  optionally grad-norm) for non-finite values and EMA-relative spikes;
* **Rollback** — on a trip it restores the last committed
  :class:`~paddle_tpu.incubate.checkpoint.AutoCheckpoint` (params, opt
  state, RNG, **data-loader position** — so the replay is exact), marks
  the poison batch window, and the :meth:`steps` iterator re-enters the
  epoch from the restored position, *skipping* the poison batches;
* **Bounded budget** — a run that keeps tripping after rolling back to
  the same checkpoint is not recoverable by replay; after
  ``max_rollbacks_per_target`` repeats (or ``max_rollbacks`` total) the
  supervisor raises :class:`~paddle_tpu.framework.errors.DivergenceError`
  with the full diagnostic instead of looping forever.

Telemetry rides the existing rails: ``("supervisor", ...)`` snapshots on
``framework.trace_events`` (analysis rule F802 fires on a rollback loop),
counters on ``framework.monitor``, and a "Training supervisor" section in
``profiler.summary()``.  Disabled (``enable=False``), every hook is a
single falsy check.

Usage::

    acp = AutoCheckpoint(model, "ckpts", save_steps=50, data_loader=loader)
    sup = TrainingSupervisor(acp)
    acp.resume()
    for epoch in range(n):
        for batch in sup.steps(loader, epoch):
            loss, _ = model.train_batch(...)
            if sup.guard(loss):
                acp.step(epoch)
        acp.epoch_end(epoch)
"""
from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, Optional

from ..framework import trace_events
from ..framework.locking import OrderedLock
from ..framework.errors import DivergenceError, InvalidArgumentError

__all__ = ["TrainingSupervisor", "DivergenceError", "stats", "record"]


# -- module-level telemetry ---------------------------------------------------
_STAT_FIELDS = ("rollbacks", "repeat_trips", "skipped_batches",
                "watchdog_trips", "exact_resumes", "fatal_divergences")
_stats: Dict[str, int] = {k: 0 for k in _STAT_FIELDS}
_stats_lock = OrderedLock("supervisor._stats_lock")


def record(field: str, n: int = 1) -> None:
    """Bump a supervisor counter and publish the ("supervisor", ...)
    snapshot (latest-value-wins family, like the other counter events).
    Called from this module, ``AutoCheckpoint.resume`` (exact_resumes)
    and the collective watchdog (watchdog_trips)."""
    with _stats_lock:
        _stats[field] = _stats.get(field, 0) + n
        snap = dict(_stats)
    from ..framework import monitor as _monitor

    _monitor.stat_add(f"supervisor_{field}", n)
    trace_events.notify(("supervisor", "supervisor"), snap)


def stats() -> Dict[str, int]:
    """Process-wide supervisor counters (rollbacks, skipped_batches,
    watchdog_trips, exact_resumes, ...)."""
    with _stats_lock:
        return dict(_stats)


class TrainingSupervisor:
    """Per-run divergence guard + rollback driver (see module doc).

    ``acp`` must be an :class:`AutoCheckpoint` constructed with
    ``data_loader=`` (or an equivalent ``attach`` provider) for the
    rollback replay to be exact; without it the re-entered epoch replays
    from its first batch.

    Divergence trips when the loss (or grad-norm) is non-finite, or — past
    ``warmup_steps`` healthy steps — exceeds ``spike_factor``× its EMA.
    ``skip_batches`` delivered batches ending at the poison one are
    skipped on replay (a NaN often comes from the *data*, and replaying
    the same batch into the same weights diverges identically).
    """

    def __init__(self, acp, *, enable: bool = True,
                 spike_factor: float = 10.0, ema_beta: float = 0.9,
                 warmup_steps: int = 5, skip_batches: int = 1,
                 max_rollbacks: int = 8, max_rollbacks_per_target: int = 1):
        if spike_factor <= 1.0:
            raise InvalidArgumentError("spike_factor must be > 1")
        if not 0.0 < ema_beta < 1.0:
            raise InvalidArgumentError("ema_beta must be in (0, 1)")
        if skip_batches < 0 or max_rollbacks < 1 or max_rollbacks_per_target < 1:
            raise InvalidArgumentError(
                "skip_batches >= 0, max_rollbacks >= 1 and "
                "max_rollbacks_per_target >= 1 required")
        self.acp = acp
        self._enabled = bool(enable)
        self.spike_factor = float(spike_factor)
        self.ema_beta = float(ema_beta)
        self.warmup_steps = int(warmup_steps)
        self.skip_batches = int(skip_batches)
        self.max_rollbacks = int(max_rollbacks)
        self.max_rollbacks_per_target = int(max_rollbacks_per_target)
        # guard state (process-local on purpose: riding the checkpoint
        # would let a rollback rewind its own budget)
        self._ema: Optional[float] = None
        self._healthy_steps = 0
        self._rollbacks = 0
        self._per_target: Dict[int, int] = {}
        # poison batch windows: {(epoch, delivered_index)} to skip on replay
        self._poison: set = set()
        self._recent: deque = deque(maxlen=64)  # (epoch, idx) yielded
        self._cur: Optional[tuple] = None
        self._restart = False
        self._epoch = 0

    # -- loop driver ---------------------------------------------------------
    def steps(self, loader, epoch: int):
        """Iterate ``loader`` for one epoch under supervision: yields
        batches, skipping poisoned ones, and — after a ``guard`` trip —
        re-creates the loader iterator so the restored data-pipeline state
        takes effect and the replay continues from the checkpointed
        position."""
        if not self._enabled:  # disabled: plain iteration, zero extra cost
            yield from loader
            return
        self._epoch = int(epoch)
        if self.acp.latest_dir() is None:
            # commit a rollback baseline before the first step: a trip on
            # step 0 must have somewhere to restore to
            self.acp.final_save(epoch, kind="baseline")
        # positions come from the DataLoader's delivered counter (absolute
        # within the epoch, survives the rollback restore); a plain
        # iterable gets a local counter — its replay restarts the epoch,
        # so local positions are absolute too
        counted = hasattr(loader, "_delivered")
        while True:
            self._restart = False
            it = iter(loader)
            local = 0
            try:
                for batch in it:
                    local += 1
                    idx = int(loader._delivered) if counted else local
                    pos = (self._epoch, idx)
                    if pos in self._poison:
                        record("skipped_batches")
                        from ..framework.logging import vlog

                        vlog(0, "supervisor: skipping poison batch "
                                "epoch=%d idx=%d", self._epoch, idx)
                        continue
                    self._cur = pos
                    self._recent.append(pos)
                    yield batch
                    if self._restart:
                        break
            finally:
                if self._restart:
                    close = getattr(it, "close", None)
                    if close is not None:
                        close()
            if not self._restart:
                return

    # -- per-step guard ------------------------------------------------------
    def guard(self, loss, grad_norm=None) -> bool:
        """Check one step's loss (and optional grad-norm).  Healthy: update
        the EMA and return True (commit the step — call ``acp.step``).
        Diverged: roll back to the last committed checkpoint, arm the
        poison-batch skip, and return False (the ``steps`` iterator
        re-enters from the restored position)."""
        if not self._enabled:
            return True
        bad = not math.isfinite(float(loss))
        if grad_norm is not None:
            bad = bad or not math.isfinite(float(grad_norm))
        if not bad and self._ema is not None and self._healthy_steps >= self.warmup_steps:
            bad = abs(float(loss)) > self.spike_factor * max(abs(self._ema), 1e-12)
        if not bad:
            v = float(loss)
            self._ema = (v if self._ema is None
                         else self.ema_beta * self._ema + (1 - self.ema_beta) * v)
            self._healthy_steps += 1
            return True
        self._trip(loss, grad_norm)
        return False

    def _trip(self, loss, grad_norm) -> None:
        from ..framework.logging import vlog

        # mark the poison window BEFORE restoring (restore resets nothing
        # here — poison state is process-local, like the budgets)
        window = list(self._recent)[-max(self.skip_batches, 0):] if self.skip_batches else []
        for pos in window:
            self._poison.add(pos)
        meta = self.acp.resume()
        if meta is None:
            record("fatal_divergences")
            raise DivergenceError(
                f"training diverged (loss={loss!r}, grad_norm={grad_norm!r}) "
                f"at epoch {self._epoch} with no committed checkpoint to "
                f"roll back to")
        target = int(meta["counter"])
        self._per_target[target] = self._per_target.get(target, 0) + 1
        repeats = self._per_target[target] - 1
        if repeats:
            record("repeat_trips")
        self._rollbacks += 1
        record("rollbacks")
        from ..framework import monitor as _monitor

        _monitor.stat_add("divergence_rollbacks")
        vlog(0, "supervisor: divergence (loss=%r) — rolled back to "
                "checkpoint %d (global step %d), skipping %d batch(es)",
             loss, target, meta.get("global_step", -1), len(window))
        if repeats >= self.max_rollbacks_per_target:
            record("fatal_divergences")
            raise DivergenceError(
                f"training re-diverged after {repeats + 1} rollback(s) to "
                f"the same checkpoint (counter {target}, global step "
                f"{meta.get('global_step')}, epoch {meta.get('epoch')}): "
                f"loss={loss!r}, grad_norm={grad_norm!r}, "
                f"{len(self._poison)} batch position(s) already "
                f"quarantined — the divergence is not batch-local "
                f"(bad LR schedule / numerics?), replay cannot fix it")
        if self._rollbacks > self.max_rollbacks:
            record("fatal_divergences")
            raise DivergenceError(
                f"rollback budget exhausted ({self._rollbacks} rollbacks > "
                f"max_rollbacks={self.max_rollbacks}) — the run keeps "
                f"diverging faster than checkpoints commit; last trip: "
                f"loss={loss!r} at epoch {self._epoch}")
        # EMA restarts its warmup: restored weights give a different loss
        # scale, and a stale EMA would instantly re-trip on the replay
        self._ema = None
        self._healthy_steps = 0
        self._restart = True

    # -- introspection -------------------------------------------------------
    @property
    def rollbacks(self) -> int:
        return self._rollbacks

    @property
    def poisoned(self) -> set:
        """Batch positions (epoch, delivered-index) quarantined so far."""
        return set(self._poison)

"""paddle_tpu.resilience — fault tolerance as a first-class subsystem.

The reference framework treats failure as API surface (the typed enforce
taxonomy of paddle/fluid/platform/enforce.h, auto-checkpoint preemption
resume, chief-side heartbeat monitoring); this package is where those
islands become a system:

* :mod:`~paddle_tpu.resilience.retry` — :class:`RetryPolicy`:
  deadline-aware exponential backoff with seeded jitter over the
  transient/fatal taxonomy (``framework.errors.is_transient``); used by
  the checkpoint async writer, ``Executor.run`` dispatch and serving
  batch execution.
* :mod:`~paddle_tpu.resilience.faults` — deterministic fault injection:
  named :func:`fault_point` hooks at the framework's I/O and dispatch
  seams, driven by a :class:`FaultPlan` (``FLAGS_fault_plan``); a no-op
  falsy check when disabled.
* :mod:`~paddle_tpu.resilience.circuit` — :class:`CircuitBreaker`:
  per-bucket closed → open → half-open degradation for the serving
  engines; open circuits shed with ``UnavailableError`` instead of
  burning device slots.
* :mod:`~paddle_tpu.resilience.preemption` — SIGTERM → one final
  synchronous checkpoint → exit :data:`PREEMPTION_EXIT_CODE` (75), which
  ``distributed.parallel.watch`` restarts without consuming the failure
  budget.

Observability rides the existing rails: counters on ``framework.monitor``,
``("resilience", ...)`` events on ``framework.trace_events`` (analysis
rule F801 flags retry storms / circuit flapping after serving warmup),
and a "Faults & retries" section in ``profiler.summary()``.
"""
from __future__ import annotations

from .circuit import CLOSED, HALF_OPEN, OPEN, CircuitBreaker  # noqa: F401
from .faults import (  # noqa: F401
    FaultPlan, FaultRule, fault_point, install_from_flags)
from .preemption import (  # noqa: F401
    PREEMPTION_EXIT_CODE, PreemptionHandler, install_preemption_handler)
from .retry import RetryPolicy, is_warm, mark_warm  # noqa: F401
from .supervisor import DivergenceError, TrainingSupervisor  # noqa: F401

from . import circuit, faults, retry, supervisor  # noqa: F401

__all__ = [
    "RetryPolicy", "mark_warm", "is_warm",
    "FaultPlan", "FaultRule", "fault_point", "install_from_flags",
    "CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN",
    "PreemptionHandler", "install_preemption_handler",
    "PREEMPTION_EXIT_CODE",
    "TrainingSupervisor", "DivergenceError",
]


# -- profiler "Faults & retries" summary section -----------------------------
_retry_base: dict = {}
_fault_base: dict = {}
_supervisor_base: dict = {}


def _on_profiler_reset() -> None:
    global _retry_base, _fault_base, _supervisor_base
    _retry_base = retry.stats()
    plan = faults._plan
    _fault_base = plan.stats() if plan is not None else {}
    _supervisor_base = supervisor.stats()


def _summary_section() -> str:
    """Activity since the last profiler reset: injected faults, retries
    per policy, and circuit state — profiler.summary() appends this."""
    lines = []
    plan = faults._plan
    if plan is not None:
        for site, d in sorted(plan.stats().items()):
            base = _fault_base.get(site, {})
            calls = d["calls"] - base.get("calls", 0)
            fired = d["fired"] - base.get("fired", 0)
            if calls or fired:
                lines.append(f"  fault {site:<24} calls {calls:>6}  "
                             f"fired {fired:>5}")
    for name, d in sorted(retry.stats().items()):
        base = _retry_base.get(name, {})
        delta = {k: d[k] - base.get(k, 0) for k in d}
        if any(delta.values()):
            lines.append(
                f"  retry {name:<24} attempts {delta['attempts']:>5}  "
                f"retries {delta['retries']:>4}  giveups "
                f"{delta['giveups'] + delta['deadline_giveups']:>4}  "
                f"after-warm {delta['retries_after_warm']:>4}")
    for name, d in sorted(circuit.all_stats().items()):
        if d["opens"] or d["sheds"] or d["open_keys"]:
            lines.append(
                f"  circuit {name:<22} opens {d['opens']:>6}  shed "
                f"{d['sheds']:>6}  open-keys {d['open_keys']:>3}  "
                f"flaps-after-warm {d['opens_after_warm']:>3}")
    if not lines:
        return ""
    return "\n".join(["Faults & retries"] + lines)


def _supervisor_section() -> str:
    """Divergence-guard activity since the last profiler reset —
    profiler.summary() appends this as "Training supervisor"."""
    d = supervisor.stats()
    delta = {k: d[k] - _supervisor_base.get(k, 0) for k in d}
    if not any(delta.values()):
        return ""
    return "\n".join([
        "Training supervisor",
        f"  rollbacks {delta.get('rollbacks', 0):>6}  "
        f"repeat-trips {delta.get('repeat_trips', 0):>4}  "
        f"fatal {delta.get('fatal_divergences', 0):>3}",
        f"  skipped-batches {delta.get('skipped_batches', 0):>6}  "
        f"exact-resumes {delta.get('exact_resumes', 0):>4}  "
        f"watchdog-trips {delta.get('watchdog_trips', 0):>4}",
    ])


def _register_profiler_section() -> None:
    from .. import profiler

    profiler.register_summary_section(_summary_section,
                                      on_reset=_on_profiler_reset)
    profiler.register_summary_section(_supervisor_section)


_register_profiler_section()

# env-driven fault plans (FLAGS_fault_plan=... in a chaos subprocess)
# install at import so every fault point in the process sees them
install_from_flags()

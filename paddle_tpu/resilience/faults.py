"""Deterministic fault injection — named fault points driven by a plan.

Recovery code that is never exercised is broken code waiting for an outage
(the autotuner needed *measured* timing for the same reason; PR 4).  This
module plants named :func:`fault_point` hooks at the I/O and dispatch seams
of the framework; a :class:`FaultPlan` decides, deterministically, which
calls fail and how.

Installed sites (grep for ``fault_point(`` to audit):

=====================  ====================================================
``serialization.save``  framework checkpoint file write (serialization.py)
``checkpoint.write``    async checkpoint worker write (incubate/checkpoint)
``executor.dispatch``   compiled-runner dispatch in ``Executor.run``
``collective.call``     every user-facing collective (distributed)
``distributed.init``    coordinator join in ``init_parallel_env`` — each
                        retried attempt passes through (distributed/env)
``gang.join``           gang membership handshake (distributed/gang)
``gang.collective``     host-lane gang collectives (distributed/gang) —
                        a ``latency_ms`` rule here wedges one rank and
                        exercises the collective-timeout watchdog
``serving.runner``      micro-batcher batch execution (serving/batcher)
``router.dispatch``     replica pick → engine submit (serving/router)
=====================  ====================================================

With no plan installed (the default) :func:`fault_point` is a single
module-global falsy check — the same zero-cost discipline as
``trace_events.active()`` — so production hot paths pay nothing and CPU
runs stay bit-identical.

A plan comes from ``FLAGS_fault_plan`` (env ``FLAGS_fault_plan=...`` — the
chaos-smoke subprocess path), or programmatically::

    plan = FaultPlan.parse("site=checkpoint.write,nth=2,error=OSError")
    with plan:                      # install() / remove() also work
        train()
    plan.stats()                    # {'checkpoint.write': {'calls': ..,
                                    #                       'fired': ..}}

Determinism: ``nth``/``every`` fire on exact per-site call counts;
probabilistic rules draw from a ``random.Random(seed)`` owned by the rule,
so the same seed and the same call sequence reproduce the same firing
pattern bit-for-bit.
"""
from __future__ import annotations

import builtins
import threading
import time
from random import Random
from typing import Dict, List, Optional

from ..framework import errors as _errors
from ..framework.errors import InvalidArgumentError

__all__ = ["FaultRule", "FaultPlan", "fault_point", "install", "remove",
           "active", "install_from_flags"]

#: the one installed plan; ``None`` keeps fault_point on its no-op path
_plan: Optional["FaultPlan"] = None


def fault_point(site: str) -> None:
    """Hook called on the framework's failure-injection seams.  No-op
    (one global read + falsy check) unless a :class:`FaultPlan` is
    installed and has a rule for ``site``."""
    plan = _plan
    if plan is None:
        return
    plan._hit(site)


def active() -> bool:
    return _plan is not None


def install(plan: "FaultPlan") -> "FaultPlan":
    """Make ``plan`` the process-wide fault plan (replacing any other)."""
    global _plan
    _plan = plan
    return plan


def remove() -> None:
    global _plan
    _plan = None


def _resolve_error(name: str):
    cls = getattr(_errors, name, None) or getattr(builtins, name, None)
    if cls is None or not (isinstance(cls, type)
                           and issubclass(cls, BaseException)):
        raise InvalidArgumentError(
            f"fault plan error class {name!r} is not an exception in "
            f"framework.errors or builtins")
    return cls


class FaultRule:
    """One firing rule for one site.  Exactly one trigger:

    * ``nth`` — fire on exactly the Nth call to the site (once);
    * ``every`` — fire on every Nth call;
    * ``p`` (+ ``seed``) — fire with probability ``p`` per call, drawn
      from a rule-owned seeded RNG.

    ``times`` caps total fires (any trigger).  The action is ``raise
    error(...)`` unless ``latency_ms`` is given, which sleeps instead.
    """

    def __init__(self, site: str, *, nth: Optional[int] = None,
                 every: Optional[int] = None, p: Optional[float] = None,
                 seed: int = 0, times: Optional[int] = None,
                 error: str = "TransientDeviceError",
                 latency_ms: Optional[float] = None):
        if not site:
            raise InvalidArgumentError("fault rule needs a site=")
        triggers = sum(x is not None for x in (nth, every, p))
        if triggers != 1:
            raise InvalidArgumentError(
                f"fault rule for {site!r} needs exactly one of nth=, "
                f"every=, p= (got {triggers})")
        if p is not None and not 0.0 <= p <= 1.0:
            raise InvalidArgumentError(f"p must be in [0, 1], got {p}")
        self.site = site
        self.nth = int(nth) if nth is not None else None
        self.every = int(every) if every is not None else None
        self.p = float(p) if p is not None else None
        self.seed = int(seed)
        self.times = int(times) if times is not None else None
        self.error_name = error
        self.error_cls = _resolve_error(error) if latency_ms is None else None
        self.latency_ms = float(latency_ms) if latency_ms is not None else None
        self._rng = Random(self.seed)
        self.fired = 0

    def should_fire(self, call_index: int) -> bool:
        """``call_index`` is 1-based per site.  Probabilistic rules draw
        exactly one variate per call, fire or not, so the decision stream
        is a pure function of (seed, call sequence)."""
        if self.p is not None:
            draw = self._rng.random() < self.p
        elif self.nth is not None:
            draw = call_index == self.nth
        else:
            draw = call_index % self.every == 0
        if not draw:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        return True

    def fire(self, call_index: int) -> None:
        self.fired += 1
        from ..framework import monitor as _monitor
        from ..framework import trace_events

        _monitor.stat_add("fault_injections")
        if trace_events.active():
            trace_events.notify(
                ("resilience", f"fault:{self.site}"),
                {"kind": "fault", "site": self.site, "call": call_index,
                 "fired": self.fired,
                 "action": ("latency" if self.latency_ms is not None
                            else self.error_name)})
        if self.latency_ms is not None:
            time.sleep(self.latency_ms / 1e3)
            return
        raise self.error_cls(
            f"injected fault at {self.site!r} (call {call_index}, "
            f"fire {self.fired})")

    def describe(self) -> str:
        trig = (f"nth={self.nth}" if self.nth is not None else
                f"every={self.every}" if self.every is not None else
                f"p={self.p},seed={self.seed}")
        act = (f"latency_ms={self.latency_ms:g}"
               if self.latency_ms is not None else self.error_name)
        tail = f",times={self.times}" if self.times is not None else ""
        return f"{self.site}[{trig}{tail} -> {act}]"


class FaultPlan:
    """A set of :class:`FaultRule`\\ s plus per-site call counters.

    Thread-safe: sites are hit from the serving worker, the checkpoint
    writer and the main thread concurrently; the decision (count + RNG
    draw) happens under one lock, the action (sleep/raise) outside it.
    """

    def __init__(self, rules: List[FaultRule]):
        self.rules = list(rules)
        self._by_site: Dict[str, List[FaultRule]] = {}
        for r in self.rules:
            self._by_site.setdefault(r.site, []).append(r)
        self._calls: Dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse the ``FLAGS_fault_plan`` mini-language (see flags.py)."""
        rules = []
        for chunk in spec.split(";"):
            chunk = chunk.strip()
            if not chunk:
                continue
            kw: Dict[str, str] = {}
            for field in chunk.split(","):
                if "=" not in field:
                    raise InvalidArgumentError(
                        f"fault plan field {field!r} is not key=value "
                        f"(in {chunk!r})")
                k, v = field.split("=", 1)
                kw[k.strip()] = v.strip()
            site = kw.pop("site", "")
            num = {k: float(v) if k in ("p", "latency_ms") else int(v)
                   for k, v in kw.items() if k != "error"}
            if "error" in kw:
                num["error"] = kw["error"]
            rules.append(FaultRule(site, **num))
        if not rules:
            raise InvalidArgumentError(
                f"fault plan {spec!r} contains no rules")
        return cls(rules)

    def _hit(self, site: str) -> None:
        with self._lock:
            rules = self._by_site.get(site)
            if rules is None:
                return
            self._calls[site] = idx = self._calls.get(site, 0) + 1
            to_fire = [r for r in rules if r.should_fire(idx)]
        for r in to_fire:  # sleep/raise outside the lock
            r.fire(idx)

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            return {site: {"calls": self._calls.get(site, 0),
                           "fired": sum(r.fired for r in rules)}
                    for site, rules in self._by_site.items()}

    def describe(self) -> str:
        return "; ".join(r.describe() for r in self.rules)

    def install(self) -> "FaultPlan":
        return install(self)

    def remove(self) -> None:
        if _plan is self:
            remove()

    def __enter__(self) -> "FaultPlan":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.remove()


def install_from_flags() -> Optional[FaultPlan]:
    """Install the plan named by ``FLAGS_fault_plan`` (usually seeded via
    the ``FLAGS_fault_plan`` env var — the chaos-smoke subprocess path).
    Returns the installed plan, or None when the flag is unset."""
    from ..framework.flags import flag

    spec = flag("fault_plan")
    if not spec:
        return None
    return install(FaultPlan.parse(spec))

"""Per-key circuit breaker for the serving layer.

One bad bucket executable (poisoned weights slice, a shape that trips a
runtime bug) must not burn a device slot per request forever: after the
failure rate over a sliding window crosses a threshold, the bucket's
circuit OPENS and requests shed immediately with ``UnavailableError`` —
the device keeps serving healthy buckets.  After a cool-down the circuit
goes HALF_OPEN and admits a limited number of probe batches; all probes
succeeding closes it, any probe failing re-opens it.

State machine (per key)::

    CLOSED --(failure rate >= threshold over full window)--> OPEN
    OPEN   --(cooldown elapsed)--> HALF_OPEN
    HALF_OPEN --(all probes succeed)--> CLOSED
    HALF_OPEN --(any probe fails)--> OPEN

Transitions are published as ``("resilience", "circuit:<name>")`` events
on ``framework.trace_events``; re-opens after serving warmup count as
*flapping* and feed analysis rule F801.
"""
from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, Optional

from ..framework import trace_events
from ..framework.errors import InvalidArgumentError
from .retry import is_warm

__all__ = ["CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN", "all_stats"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: live breakers, for the profiler "Faults & retries" summary section
_breakers: "weakref.WeakSet" = weakref.WeakSet()


def all_stats() -> Dict[str, dict]:
    """Aggregate snapshot of every live breaker, keyed by breaker name."""
    return {b.name: b.stats() for b in list(_breakers)}


class _KeyState:
    __slots__ = ("state", "outcomes", "opened_at", "probes_left",
                 "probe_successes", "opens", "opens_after_warm", "sheds",
                 "failures", "successes")

    def __init__(self, window: int):
        self.state = CLOSED
        self.outcomes: deque = deque(maxlen=window)  # True = success
        self.opened_at = 0.0
        self.probes_left = 0
        self.probe_successes = 0
        self.opens = 0
        self.opens_after_warm = 0
        self.sheds = 0
        self.failures = 0
        self.successes = 0


class CircuitBreaker:
    """Failure-rate circuit breaker over arbitrary hashable keys (the
    serving engines key by bucket index).

    Call :meth:`allow` before doing the work; on False, shed.  Report the
    outcome with :meth:`record_success` / :meth:`record_failure`.  All
    three are thread-safe.  Defaults come from the ``FLAGS_circuit_*``
    flags; ``clock`` is injectable for tests.
    """

    def __init__(self, name: str = "circuit", *,
                 failure_threshold: Optional[float] = None,
                 window: Optional[int] = None,
                 cooldown_ms: Optional[float] = None,
                 half_open_probes: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ..framework.flags import flag

        self.name = name
        self.failure_threshold = float(
            failure_threshold if failure_threshold is not None
            else flag("circuit_failure_threshold"))
        if not 0.0 < self.failure_threshold <= 1.0:
            raise InvalidArgumentError(
                "circuit failure_threshold must be in (0, 1]")
        self.window = int(window if window is not None
                          else flag("circuit_window"))
        if self.window < 1:
            raise InvalidArgumentError("circuit window must be >= 1")
        self.cooldown_s = float(cooldown_ms if cooldown_ms is not None
                                else flag("circuit_cooldown_ms")) / 1e3
        self.half_open_probes = int(
            half_open_probes if half_open_probes is not None
            else flag("circuit_half_open_probes"))
        if self.half_open_probes < 1:
            raise InvalidArgumentError("half_open_probes must be >= 1")
        self._clock = clock
        self._lock = threading.Lock()
        self._keys: Dict[object, _KeyState] = {}
        _breakers.add(self)

    def _key(self, key) -> _KeyState:
        ks = self._keys.get(key)
        if ks is None:
            ks = self._keys[key] = _KeyState(self.window)
        return ks

    # -- decision ------------------------------------------------------------
    def allow(self, key) -> bool:
        """May work for ``key`` proceed?  False means shed now (and the
        shed is counted); an OPEN circuit whose cooldown has elapsed
        transitions to HALF_OPEN here and admits probes."""
        with self._lock:
            ks = self._key(key)
            if ks.state == CLOSED:
                return True
            if ks.state == OPEN:
                if self._clock() - ks.opened_at < self.cooldown_s:
                    ks.sheds += 1
                    return False
                ks.state = HALF_OPEN
                ks.probes_left = self.half_open_probes
                ks.probe_successes = 0
                self._publish(key, ks, "half_open")
            # HALF_OPEN: admit up to half_open_probes in-flight probes
            if ks.probes_left > 0:
                ks.probes_left -= 1
                return True
            ks.sheds += 1
            return False

    # -- outcome reporting ---------------------------------------------------
    def record_success(self, key) -> None:
        with self._lock:
            ks = self._key(key)
            ks.successes += 1
            if ks.state == HALF_OPEN:
                ks.probe_successes += 1
                if ks.probe_successes >= self.half_open_probes:
                    ks.state = CLOSED
                    ks.outcomes.clear()
                    self._publish(key, ks, "closed")
                return
            if ks.state == CLOSED:
                ks.outcomes.append(True)

    def record_failure(self, key) -> None:
        with self._lock:
            ks = self._key(key)
            ks.failures += 1
            if ks.state == HALF_OPEN:
                self._open(key, ks)  # a failed probe re-opens immediately
                return
            if ks.state != CLOSED:
                return
            ks.outcomes.append(False)
            if len(ks.outcomes) < self.window:
                return  # never judge a partial window
            rate = ks.outcomes.count(False) / len(ks.outcomes)
            if rate >= self.failure_threshold:
                self._open(key, ks)

    def _open(self, key, ks: _KeyState) -> None:
        ks.state = OPEN
        ks.opened_at = self._clock()
        ks.opens += 1
        if is_warm():
            ks.opens_after_warm += 1
        ks.outcomes.clear()
        from ..framework import monitor as _monitor

        _monitor.stat_add("circuit_opens")
        self._publish(key, ks, "open")

    def _publish(self, key, ks: _KeyState, transition: str) -> None:
        if not trace_events.active():
            return
        trace_events.notify(
            ("resilience", f"circuit:{self.name}"),
            {"kind": "circuit", "key": key, "transition": transition,
             "state": ks.state, "opens": ks.opens,
             "opens_after_warm": ks.opens_after_warm,
             "failures": ks.failures, "successes": ks.successes,
             "sheds": ks.sheds})

    def reset(self, key) -> None:
        """Forget ``key``'s window and state entirely (back to CLOSED).
        The serving router uses this when a drained replica is re-admitted
        after a weight swap: outcomes recorded against the old weights
        must not prejudice the new ones."""
        with self._lock:
            self._keys.pop(key, None)

    # -- introspection -------------------------------------------------------
    def state(self, key) -> str:
        with self._lock:
            ks = self._keys.get(key)
            return ks.state if ks is not None else CLOSED

    def stats(self) -> dict:
        """Aggregate + per-key counters (keys stringified for JSON)."""
        with self._lock:
            per_key = {
                str(k): {"state": ks.state, "opens": ks.opens,
                         "opens_after_warm": ks.opens_after_warm,
                         "sheds": ks.sheds, "failures": ks.failures,
                         "successes": ks.successes}
                for k, ks in self._keys.items()}
        agg = {f: sum(d[f] for d in per_key.values())
               for f in ("opens", "opens_after_warm", "sheds", "failures",
                         "successes")}
        agg["open_keys"] = sum(1 for d in per_key.values()
                               if d["state"] != CLOSED)
        agg["keys"] = per_key
        return agg

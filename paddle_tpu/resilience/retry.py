"""Deadline-aware retry with exponential backoff and seeded jitter.

:class:`RetryPolicy` is the one retry primitive the framework uses — the
checkpoint async writer, ``Executor.run`` dispatch and serving batch
execution all route transient failures (``errors.is_transient``) through
it, so retry behavior is configured in exactly one place
(``FLAGS_transient_max_retries`` / ``FLAGS_retry_backoff_ms``).

Observability: every retry bumps a per-policy counter registry (surfaced
in ``profiler.summary()``'s "Faults & retries" section and as
``("resilience", "retry:<name>")`` trace events), plus the global
``monitor.stat_add("transient_retries")``.  Retries that happen *after*
:func:`mark_warm` — i.e. inside a warmed serving hot path — are counted
separately; sustained ``retries_after_warm`` is what analysis rule F801
calls a retry storm.
"""
from __future__ import annotations

import threading
import time
from random import Random
from typing import Callable, Dict, Optional, Tuple, Union

from ..framework import trace_events
from ..framework.errors import InvalidArgumentError, is_transient

__all__ = ["RetryPolicy", "mark_warm", "is_warm", "stats", "reset_stats"]

_COUNTER_KEYS = ("attempts", "retries", "giveups", "deadline_giveups",
                 "retries_after_warm")

_lock = threading.Lock()
_stats: Dict[str, Dict[str, int]] = {}
_warm = False  # set by serving warmup; retries past it feed rule F801


def mark_warm() -> None:
    """Serving engines call this after ``warmup()``: retries from here on
    are hot-path events (they stall live requests) and count toward the
    F801 retry-storm rule."""
    global _warm
    _warm = True


def is_warm() -> bool:
    return _warm


def _bump(name: str, key: str, n: int = 1) -> None:
    with _lock:
        d = _stats.setdefault(name, {k: 0 for k in _COUNTER_KEYS})
        d[key] += n


def stats(name: Optional[str] = None):
    """Per-policy retry counters: one dict for ``name``, or all."""
    with _lock:
        if name is not None:
            return dict(_stats.get(name, {k: 0 for k in _COUNTER_KEYS}))
        return {k: dict(v) for k, v in _stats.items()}


def reset_stats() -> None:
    with _lock:
        _stats.clear()


def _publish(name: str) -> None:
    if not trace_events.active():
        return
    snap = stats(name)
    snap["kind"] = "retry"
    trace_events.notify(("resilience", f"retry:{name}"), snap)


class RetryPolicy:
    """Bounded retry of transient failures.

    ``max_attempts`` total calls (1 = no retry); between attempts sleeps
    ``backoff_ms * multiplier**i`` capped at ``max_backoff_ms``, scaled by
    a jitter factor in ``[1-jitter, 1+jitter]`` drawn from a policy-owned
    ``random.Random(seed)`` — two policies with the same seed produce the
    same backoff schedule, so chaos runs are reproducible.

    ``deadline_ms`` bounds the whole call including backoff: a retry whose
    sleep would cross the deadline is abandoned and the last error raised
    — a caller-facing latency budget is never silently exceeded.

    ``retry_on``: exception classifier — a predicate or a tuple of types;
    default :func:`framework.errors.is_transient`.  Non-matching errors
    propagate immediately, attempt 1.

    ``sleep``/``clock`` are injectable for tests.
    """

    def __init__(self, max_attempts: Optional[int] = None,
                 backoff_ms: Optional[float] = None, multiplier: float = 2.0,
                 max_backoff_ms: Optional[float] = None, jitter: float = 0.25,
                 seed: int = 0, deadline_ms: Optional[float] = None,
                 retry_on: Union[None, Callable, Tuple] = None,
                 name: str = "retry",
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        from ..framework.flags import flag

        self.max_attempts = int(max_attempts if max_attempts is not None
                                else flag("transient_max_retries"))
        if self.max_attempts < 1:
            raise InvalidArgumentError("max_attempts must be >= 1")
        self.backoff_ms = float(backoff_ms if backoff_ms is not None
                                else flag("retry_backoff_ms"))
        self.multiplier = float(multiplier)
        self.max_backoff_ms = float(max_backoff_ms if max_backoff_ms
                                    is not None else 20 * self.backoff_ms)
        if not 0.0 <= jitter < 1.0:
            raise InvalidArgumentError("jitter must be in [0, 1)")
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.deadline_ms = (float(deadline_ms) if deadline_ms is not None
                            else None)
        if retry_on is None:
            self._retryable = is_transient
        elif callable(retry_on) and not isinstance(retry_on, type):
            self._retryable = retry_on
        else:
            classes = retry_on if isinstance(retry_on, tuple) else (retry_on,)
            self._retryable = lambda e: isinstance(e, classes)
        self.name = name
        self._sleep = sleep
        self._clock = clock
        self._rng = Random(self.seed)

    @classmethod
    def from_flags(cls, name: str = "retry", **overrides) -> "RetryPolicy":
        """The flag-configured default policy used by the executor,
        checkpoint writer and serving runner."""
        return cls(name=name, **overrides)

    def delay_s(self, retry_index: int) -> float:
        """Backoff before the ``retry_index``-th retry (0-based), in
        seconds, consuming one jitter draw from the policy RNG."""
        base = min(self.backoff_ms * self.multiplier ** retry_index,
                   self.max_backoff_ms)
        factor = 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return base * factor / 1e3

    def schedule(self, n: Optional[int] = None):
        """The first ``n`` (default ``max_attempts - 1``) backoff delays a
        fresh policy with this seed would sleep — for tests and docs; does
        not consume this instance's RNG."""
        probe = RetryPolicy(
            max_attempts=self.max_attempts, backoff_ms=self.backoff_ms,
            multiplier=self.multiplier, max_backoff_ms=self.max_backoff_ms,
            jitter=self.jitter, seed=self.seed, name=self.name)
        return [probe.delay_s(i)
                for i in range(n if n is not None else self.max_attempts - 1)]

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying transient failures.
        Raises the last error when attempts, the deadline, or the
        classifier say stop."""
        return self._call(self.deadline_ms, fn, args, kwargs)

    def call_deadline(self, deadline_ms: Optional[float], fn: Callable,
                      *args, **kwargs):
        """Like :meth:`call`, additionally bounded by a caller-supplied
        latency budget (e.g. the tightest per-request deadline of a
        serving batch).  The effective deadline is the tighter of
        ``deadline_ms`` and the policy's own ``deadline_ms`` — backoff
        sleeps never blow through either."""
        if deadline_ms is None:
            effective = self.deadline_ms
        elif self.deadline_ms is None:
            effective = float(deadline_ms)
        else:
            effective = min(float(deadline_ms), self.deadline_ms)
        return self._call(effective, fn, args, kwargs)

    def _call(self, deadline_ms: Optional[float], fn: Callable, args,
              kwargs):
        deadline = (self._clock() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        attempt = 0
        while True:
            attempt += 1
            _bump(self.name, "attempts")
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                if not self._retryable(e):
                    raise
                if attempt >= self.max_attempts:
                    _bump(self.name, "giveups")
                    _publish(self.name)
                    raise
                delay = self.delay_s(attempt - 1)
                if deadline is not None and self._clock() + delay > deadline:
                    _bump(self.name, "deadline_giveups")
                    _publish(self.name)
                    raise
                _bump(self.name, "retries")
                if _warm:
                    _bump(self.name, "retries_after_warm")
                from ..framework import monitor as _monitor

                _monitor.stat_add("transient_retries")
                _publish(self.name)
                self._sleep(delay)

    def __call__(self, fn: Callable) -> Callable:
        """Decorator form."""
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        return wrapped

"""Graceful preemption: SIGTERM → one final checkpoint → distinct exit.

TPU pods are preempted with SIGTERM ahead of SIGKILL; a run that treats
that as a crash loses up to ``save_steps`` of work and burns one unit of
the watchdog's restart budget per eviction.  :class:`PreemptionHandler`
installs a SIGTERM handler that writes ONE final synchronous checkpoint
(``AutoCheckpoint.final_save`` — meta-last, so a SIGKILL landing mid-write
still leaves the previous checkpoint committed) and exits with
:data:`PREEMPTION_EXIT_CODE`.

``distributed.parallel.watch`` recognizes that exit code as a *clean
preemption*: the trainer is restarted WITHOUT consuming the
``max_restarts`` failure budget — evictions are the platform's fault, not
the trainer's.

The exit code is 75 (BSD ``EX_TEMPFAIL`` — "temporary failure, retry"),
deliberately distinct from 143 (default SIGTERM death) so a trainer that
died *without* saving still consumes budget.
"""
from __future__ import annotations

import os
import signal
import threading
from typing import Callable, Optional

__all__ = ["PreemptionHandler", "install_preemption_handler",
           "PREEMPTION_EXIT_CODE"]

#: sysexits.h EX_TEMPFAIL: the contract between the SIGTERM handler and
#: the ``parallel.watch`` watchdog (restart without consuming budget)
PREEMPTION_EXIT_CODE = 75


class PreemptionHandler:
    """SIGTERM → ``on_preempt()`` → ``checkpoint.final_save(epoch)`` →
    ``exit(75)``.

    ``checkpoint`` is an ``incubate.checkpoint.AutoCheckpoint`` (anything
    with ``final_save(epoch)``), or ``None`` for serving processes that
    have no training state to save; ``get_epoch`` supplies the epoch
    stamped into the final checkpoint (default: the last epoch the
    checkpoint object saw).  ``on_preempt`` is an optional best-effort
    hook that runs FIRST — the serving router passes its
    ``drain_all`` here so an eviction finishes in-flight requests before
    the process exits.  Install from the MAIN thread (CPython delivers
    signals there).  ``_exit`` is injectable for tests.
    """

    def __init__(self, checkpoint=None,
                 get_epoch: Optional[Callable[[], int]] = None,
                 exit_code: int = PREEMPTION_EXIT_CODE,
                 _exit: Callable[[int], None] = os._exit,
                 on_preempt: Optional[Callable[[], None]] = None):
        self.checkpoint = checkpoint
        self.get_epoch = get_epoch
        self.on_preempt = on_preempt
        self.exit_code = int(exit_code)
        self._exit = _exit
        self._old_handler = None
        self._installed = False
        self._fired = threading.Event()

    def install(self) -> "PreemptionHandler":
        self._old_handler = signal.signal(signal.SIGTERM, self._on_sigterm)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            signal.signal(signal.SIGTERM, self._old_handler)
            self._installed = False

    def _on_sigterm(self, signum, frame) -> None:
        if self._fired.is_set():  # a second SIGTERM mid-save: just die
            self._exit(self.exit_code)
            return
        self._fired.set()
        from ..framework import monitor as _monitor
        from ..framework import trace_events
        from ..framework.logging import vlog

        _monitor.stat_add("preemptions")
        if self.on_preempt is not None:
            try:
                self.on_preempt()
            except BaseException as e:  # noqa: BLE001 — draining is best
                # effort; a stuck drain must not block the exit the
                # platform is about to force with SIGKILL
                _monitor.stat_add("preemption_drain_failures")
                vlog(0, "preemption: on_preempt hook FAILED (%s: %s) — "
                        "continuing to exit", type(e).__name__, e)
        epoch = None
        try:
            if self.checkpoint is not None:
                epoch = (self.get_epoch() if self.get_epoch is not None
                         else getattr(self.checkpoint, "last_epoch", 0))
                self.checkpoint.final_save(int(epoch))
                vlog(0, "preemption: final checkpoint saved (epoch %s), "
                        "exiting %d", epoch, self.exit_code)
        except BaseException as e:  # noqa: BLE001 — the save is best
            # effort; a failed final save must still exit promptly (the
            # previous committed checkpoint stays the resume point)
            _monitor.stat_add("preemption_save_failures")
            vlog(0, "preemption: final save FAILED (%s: %s) — exiting %d "
                    "anyway; resume falls back to the last committed "
                    "checkpoint", type(e).__name__, e, self.exit_code)
        if trace_events.active():
            trace_events.notify(("resilience", "preemption"),
                                {"kind": "preemption", "epoch": epoch,
                                 "exit_code": self.exit_code})
        self._exit(self.exit_code)

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


def install_preemption_handler(checkpoint,
                               get_epoch: Optional[Callable[[], int]] = None
                               ) -> PreemptionHandler:
    """Convenience: build and install a :class:`PreemptionHandler`.

    >>> acp = AutoCheckpoint(model, "ckpts", save_steps=100)
    >>> handler = install_preemption_handler(acp)
    >>> ...train...
    >>> handler.uninstall()
    """
    return PreemptionHandler(checkpoint, get_epoch=get_epoch).install()

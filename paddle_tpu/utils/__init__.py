"""paddle_tpu.utils — install check, deprecation, lazy import, naming,
downloads, profiler driver.

Parity: python/paddle/utils/ (install_check.py:134 run_check,
deprecated.py:31, lazy_import.py:19 try_import, profiler.py, download.py,
op_version.py) + the fluid framework utilities re-exported there
(unique_name, require_version, load_op_library).
"""
from __future__ import annotations

import functools
import importlib
import warnings

from . import unique_name  # noqa: F401
from . import download  # noqa: F401
from .profiler import Profiler, ProfilerOptions, get_profiler  # noqa: F401

__all__ = ["run_check", "deprecated", "try_import", "unique_name",
           "download", "Profiler", "ProfilerOptions", "get_profiler",
           "require_version", "load_op_library", "OpLastCheckpointChecker"]


def require_version(min_version: str, max_version=None):
    """Assert the installed framework version is in range (ref:
    fluid/framework.py require_version).  Compares dot-release tuples;
    a development build ('0.0.0'-style or git suffix) passes."""
    from ..version import __version__

    def parse(v):
        parts = []
        for piece in str(v).split("."):
            digits = "".join(ch for ch in piece if ch.isdigit())
            if digits == "":
                break
            parts.append(int(digits))
        return tuple(parts)

    if not isinstance(min_version, str) or (
            max_version is not None and not isinstance(max_version, str)):
        raise TypeError("require_version: versions must be strings")
    cur = parse(__version__)
    if not cur or cur[0] == 0:
        return  # 0.x dev build — version gates are for released majors
    if parse(min_version) > cur:
        raise Exception(
            f"installed paddle_tpu {__version__} < required minimum "
            f"{min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed paddle_tpu {__version__} > required maximum "
            f"{max_version}")


def load_op_library(lib_filename: str):
    """Custom C++ op loading (ref: fluid/framework.py load_op_library,
    .so of REGISTER_OPERATOR ops).  There is no OpKernel registry to
    extend — custom ops are jax ops (pure functions, optionally Pallas
    kernels); raises with that migration path."""
    from ..framework.errors import UnimplementedError

    raise UnimplementedError(
        "load_op_library: no operator registry exists — write the op as "
        "a jax function (optionally a Pallas kernel, see "
        "paddle_tpu/ops/flash_attention.py for the pattern) and call it "
        "directly; host C/C++ code can be reached via jax.pure_callback "
        "or ctypes (paddle_tpu/native/ingest.cc pattern)")


class OpLastCheckpointChecker:
    """Op-version compatibility probe (ref: utils/op_version.py).  Ops
    here have no version registry (XLA HLO is the contract), so every
    query reports the op as current: empty mod list, version 0."""

    def get_op_attrs(self, op_name):
        return []

    def get_version(self, op_name):
        return 0


def try_import(module_name: str):
    """Import a module with an actionable error (ref: lazy_import.py:19)."""
    install_name = {"cv2": "opencv-python", "PIL": "pillow"}.get(
        module_name, module_name)
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            f"{e}\n  required module {module_name!r} is missing — "
            f"pip install {install_name}") from e


def deprecated(update_to: str = "", since: str = "", reason: str = ""):
    """Deprecation decorator (ref: deprecated.py:31): extends the
    docstring and warns DeprecationWarning on call."""

    def decorator(fn):
        note = "Warning: this API is deprecated"
        if since:
            note += f" since {since}"
        if update_to:
            note += f", use {update_to} instead"
        if reason:
            note += f" ({reason})"
        fn.__doc__ = f"{note}.\n\n{fn.__doc__ or ''}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with warnings.catch_warnings():
                # default filters hide DeprecationWarning outside __main__;
                # the reference forces visibility the same way
                warnings.simplefilter("always", DeprecationWarning)
                warnings.warn(note, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return decorator


def run_check():
    """Sanity-check the install (ref: install_check.py:134): run a tiny
    train step on the available backend, and — when more than one device
    is visible — a data-parallel step over all of them."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer as popt
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed import mesh as _mesh
    from paddle_tpu.framework import random as _random

    devices = jax.devices()
    backend = jax.default_backend()
    print(f"Running verify on {len(devices)} {backend} device(s) ...")

    def one_step(use_fleet):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        if use_fleet:
            fleet.init(is_collective=True,
                       strategy=fleet.DistributedStrategy())
            opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.1))
        else:
            opt = popt.SGD(learning_rate=0.1)
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        model.prepare(optimizer=opt, loss=nn.MSELoss())
        rng = np.random.RandomState(0)
        n = max(len(devices) * 2, 4)
        x = rng.randn(n, 8).astype(np.float32)
        y = rng.randn(n, 1).astype(np.float32)
        loss, _ = model.train_batch([x], [y])
        if not np.isfinite(loss):
            raise RuntimeError(f"run_check train step produced {loss}")

    # a sanity check must not perturb the session: snapshot the RNG and
    # the fleet/mesh globals it touches, restore on the way out
    saved_rng = _random.get_rng_state()
    saved_mesh = _mesh._global_mesh
    saved_strategy = fleet._strategy
    saved_initialized = fleet._initialized
    try:
        one_step(use_fleet=False)
        print("paddle_tpu works on 1 device.")
        if len(devices) > 1:
            one_step(use_fleet=True)
            print(f"paddle_tpu works on {len(devices)} devices "
                  f"(data parallel).")
        print("paddle_tpu is installed successfully!")
    finally:
        _random.set_rng_state(saved_rng)
        _mesh._global_mesh = saved_mesh
        fleet._strategy = saved_strategy
        fleet._initialized = saved_initialized

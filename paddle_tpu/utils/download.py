"""paddle.utils.download — cached artifact resolution.

Parity: python/paddle/utils/download.py (get_weights_path_from_url,
get_path_from_url).  This environment has no network egress, so the
resolution order is: already-local path → populated cache hit
(``~/.cache/paddle_tpu/<name>``, md5-checked when given) → a clear
error telling the user where to place the file — never a silent hang
on a socket.
"""
from __future__ import annotations

import hashlib
import os
import os.path as osp

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = osp.expanduser("~/.cache/paddle_tpu/hapi/weights")
DOWNLOAD_HOME = osp.expanduser("~/.cache/paddle_tpu/download")


def _md5check(fullname: str, md5sum=None) -> bool:
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def get_path_from_url(url: str, root_dir: str = DOWNLOAD_HOME, md5sum=None,
                      check_exist: bool = True) -> str:
    """Resolve ``url`` to a local file (ref: download.py get_path_from_url
    — minus the actual fetch, which needs egress)."""
    if osp.exists(url):  # already a local path
        return url
    fname = osp.split(url)[-1]
    fullname = osp.join(root_dir, fname)
    if check_exist and osp.exists(fullname) and _md5check(fullname, md5sum):
        return fullname
    raise RuntimeError(
        f"cannot download {url!r}: this environment has no network "
        f"egress.  Place the file at {fullname!r} (it will be md5-checked "
        f"and used as a cache hit) and retry")


def get_weights_path_from_url(url: str, md5sum=None) -> str:
    """Pretrained-weight resolution (ref: download.py
    get_weights_path_from_url) — same cache contract, weights directory."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)

"""paddle.utils.unique_name — session-unique name generation.

Parity: python/paddle/fluid/unique_name.py (generate:84, switch:134,
guard:187).  Names are purely cosmetic here (parameters live in Layer
attribute paths, not a global Scope), but user code and ParamAttr
defaults still ask for fresh names.
"""
from __future__ import annotations

import contextlib

__all__ = ["generate", "guard", "switch"]


class UniqueNameGenerator:
    """Counter-per-prefix generator (ref: unique_name.py:33)."""

    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids: dict = {}

    def __call__(self, key: str) -> str:
        tmp = self.ids.setdefault(key, 0)
        self.ids[key] = tmp + 1
        return self.prefix + "_".join([key, str(tmp)])


generator = UniqueNameGenerator()


def generate(key: str) -> str:
    """``key`` → ``key_<n>``, unique within the active generator."""
    return generator(key)


def switch(new_generator=None):
    """Swap the active generator, returning the old one (ref :134)."""
    global generator
    old = generator
    generator = new_generator if new_generator is not None \
        else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope a fresh (or prefixed) generator (ref :187): inside the
    guard, counters restart — two models built under different guards
    can reuse names without collision."""
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    elif isinstance(new_generator, bytes):
        new_generator = UniqueNameGenerator(new_generator.decode())
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)

"""paddle.utils.profiler — batch-range profiler driver.

Parity: python/paddle/utils/profiler.py (ProfilerOptions:26, Profiler:63,
get_profiler:131) over the paddle_tpu.profiler engine (host event table
+ jax.profiler device traces; see profiler.py for the TPU-native design
replacing CUPTI, SURVEY §5).
"""
from __future__ import annotations

import sys
import warnings

from ..profiler import start_profiler, stop_profiler, reset_profiler

__all__ = ["ProfilerOptions", "Profiler", "get_profiler"]


class ProfilerOptions:
    """Option bag with the reference's keys/defaults (utils/profiler.py:26)."""

    def __init__(self, options=None):
        self.options = {
            "state": "All",
            "sorted_key": "default",
            "tracer_level": "Default",
            "batch_range": [0, sys.maxsize],
            "output_thread_detail": False,
            "profile_path": "none",
            "timeline_path": "none",
            "op_summary_path": "none",
        }
        if options is not None:
            for key in self.options:
                if options.get(key) is not None:
                    self.options[key] = options[key]

    def with_state(self, state):
        self.options["state"] = state
        return self

    def __getitem__(self, name):
        if self.options.get(name) is None:
            raise ValueError(f"ProfilerOptions has no option named {name}")
        v = self.options[name]
        return None if isinstance(v, str) and v == "none" else v


_current_profiler = None


class Profiler:
    """Context manager profiling a batch range (utils/profiler.py:63):
    ``record_step()`` each iteration; profiling starts/stops when
    ``batch_id`` crosses ``batch_range``."""

    def __init__(self, enabled=True, options=None):
        self.profiler_options = (options if options is not None
                                 else ProfilerOptions())
        self.batch_id = 0
        self.enabled = enabled
        self._running = False

    def __enter__(self):
        global _current_profiler
        self.previous_profiler = _current_profiler
        _current_profiler = self
        if self.enabled and self.profiler_options["batch_range"][0] == 0:
            self.start()
        return self

    def __exit__(self, exc_type, exc_value, tb):
        global _current_profiler
        _current_profiler = self.previous_profiler
        if self.enabled:
            self.stop()

    def start(self):
        if self.enabled and not self._running:
            try:
                start_profiler(state=self.profiler_options["state"])
                self._running = True
            except Exception as e:  # match reference's warn-don't-raise
                warnings.warn(f"Profiler not enabled: {e}")

    def stop(self):
        if self.enabled and self._running:
            try:
                stop_profiler(
                    sorted_key=self.profiler_options["sorted_key"],
                    profile_path=self.profiler_options["profile_path"])
                self._running = False
            except Exception as e:
                warnings.warn(f"Profiler not disabled: {e}")

    def reset(self):
        if self.enabled and self._running:
            reset_profiler()

    def record_step(self, change_profiler_status=True):
        if not self.enabled:
            return
        self.batch_id += 1
        if change_profiler_status:
            lo, hi = self.profiler_options["batch_range"]
            if self.batch_id == lo:
                self.reset() if self._running else self.start()
            if self.batch_id == hi:
                self.stop()


def get_profiler():
    """The innermost active Profiler, creating a default one if none
    (utils/profiler.py:131)."""
    global _current_profiler
    if _current_profiler is None:
        _current_profiler = Profiler()
    return _current_profiler

"""Probability distributions.

Parity: python/paddle/distribution.py (Distribution:40, Uniform, Normal,
Categorical — sample/entropy/log_prob/probs/kl_divergence).  The reference
assembles these from fluid ops with static/dygraph branches; here each is a
thin jax.numpy formulation (sampling draws keys from the framework
generator, so ``paddle.seed`` reproduces sample streams).
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .framework import random as _random
from .framework.errors import InvalidArgumentError

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _as_array(x, dtype=jnp.float32):
    if isinstance(x, (int, float, list, tuple, np.ndarray)):
        return jnp.asarray(x, dtype)
    return jnp.asarray(x)


def _key(seed: int) -> jax.Array:
    if seed:
        return jax.random.PRNGKey(seed)
    return _random.default_generator().next_key()


class Distribution:
    """Abstract base (parity: distribution.py:40)."""

    def sample(self, shape: Sequence[int] = (), seed: int = 0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError

    def kl_divergence(self, other: "Distribution"):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) with elementwise broadcastable bounds."""

    def __init__(self, low, high, name=None):
        self.low = _as_array(low)
        self.high = _as_array(high)

    def sample(self, shape: Sequence[int] = (), seed: int = 0):
        base = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        u = jax.random.uniform(_key(seed), tuple(shape) + base,
                               dtype=self.low.dtype)
        return self.low + u * (self.high - self.low)

    def entropy(self):
        return jnp.log(self.high - self.low)

    def _inside(self, value):
        # strict bounds both ends — reference Uniform.log_prob uses
        # ``low < value`` and ``value < high``
        return (value > self.low) & (value < self.high)

    def log_prob(self, value):
        value = _as_array(value)
        dens = jnp.where(self._inside(value), 1.0 / (self.high - self.low), 0.0)
        return jnp.log(dens)  # -inf outside the support

    def probs(self, value):
        value = _as_array(value)
        return jnp.where(self._inside(value), 1.0 / (self.high - self.low), 0.0)


class Normal(Distribution):
    """N(loc, scale^2), elementwise."""

    def __init__(self, loc, scale, name=None):
        self.loc = _as_array(loc)
        self.scale = _as_array(scale)

    def sample(self, shape: Sequence[int] = (), seed: int = 0):
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        z = jax.random.normal(_key(seed), tuple(shape) + base,
                              dtype=self.loc.dtype)
        return self.loc + z * self.scale

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)

    def log_prob(self, value):
        value = _as_array(value)
        var = jnp.square(self.scale)
        return (-jnp.square(value - self.loc) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def probs(self, value):
        return jnp.exp(self.log_prob(value))

    def kl_divergence(self, other: "Normal"):
        """KL(self || other), elementwise (reference: Normal.kl_divergence)."""
        if not isinstance(other, Normal):
            raise InvalidArgumentError("kl_divergence expects another Normal")
        var_ratio = jnp.square(self.scale / other.scale)
        t1 = jnp.square((self.loc - other.loc) / other.scale)
        return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))


class Categorical(Distribution):
    """Categorical over the last axis of ``logits`` (unnormalized)."""

    def __init__(self, logits, name=None):
        self.logits = _as_array(logits)
        if self.logits.ndim < 1:
            raise InvalidArgumentError("Categorical logits must be >= 1-D")

    def _log_pmf(self):
        return self.logits - jax.nn.logsumexp(self.logits, axis=-1,
                                              keepdims=True)

    def sample(self, shape: Sequence[int] = (), seed: int = 0):
        return jax.random.categorical(
            _key(seed), self.logits, axis=-1,
            shape=tuple(shape) + self.logits.shape[:-1])

    def entropy(self):
        logp = self._log_pmf()
        return -(jnp.exp(logp) * logp).sum(-1)

    def probs(self, value):
        value = jnp.asarray(value, jnp.int32)
        p = jnp.exp(self._log_pmf())
        return jnp.take_along_axis(p, value[..., None], axis=-1)[..., 0]

    def log_prob(self, value):
        value = jnp.asarray(value, jnp.int32)
        return jnp.take_along_axis(self._log_pmf(), value[..., None],
                                   axis=-1)[..., 0]

    def kl_divergence(self, other: "Categorical"):
        if not isinstance(other, Categorical):
            raise InvalidArgumentError(
                "kl_divergence expects another Categorical")
        logp = self._log_pmf()
        logq = other._log_pmf()
        return (jnp.exp(logp) * (logp - logq)).sum(-1)

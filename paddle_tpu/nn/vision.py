"""Alias module: paddle.nn.vision (ref: python/paddle/nn/layer/vision.py
holds PixelShuffle at this version; the class lives in common.py here)."""
from .common import PixelShuffle  # noqa: F401

__all__ = ["PixelShuffle"]

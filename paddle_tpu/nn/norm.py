"""Norm layers.

Parity surface: paddle.nn.BatchNorm1D/2D/3D, LayerNorm, GroupNorm,
InstanceNorm, SyncBatchNorm, SpectralNorm, LocalResponseNorm
(reference: python/paddle/nn/layer/norm.py over operators/batch_norm_op.*).

BatchNorm running stats are ``Buffer``s; in eager training mode the layer
assigns the updated stats back into its buffers, and under
``functional_call(..., return_buffers=True)`` the updates are captured
functionally (no side effects leak into a jit trace).

SyncBatchNorm: cross-replica stats via a mesh-axis psum when called inside
shard_map/pjit with a data axis present — the TPU-native equivalent of the
reference's sync_batch_norm_op.cu (NCCL allreduce of partial sums).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import functional as F
from . import initializer as I
from .layer_base import Layer

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
    "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", jnp.zeros((num_features,), jnp.float32))
        self.register_buffer("_variance", jnp.ones((num_features,), jnp.float32))

    def forward(self, x):
        out = F.batch_norm(
            x, self._mean.value, self._variance.value,
            self.weight.value if self.weight is not None else None,
            self.bias.value if self.bias is not None else None,
            training=self.training, momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format, use_global_stats=self.use_global_stats)
        if isinstance(out, tuple):
            out, new_mean, new_var = out
            self._mean.value = new_mean
            self._variance.value = new_var
        return out


class BatchNorm(_BatchNormBase):
    """Legacy fluid.dygraph.BatchNorm parity (accepts act=None)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 data_format="NCHW", **kwargs):
        super().__init__(num_channels, momentum=momentum, epsilon=epsilon,
                         data_format=data_format)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            out = F.relu(out)
        elif self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN (ref: operators/sync_batch_norm_op.cu — NCCL partial
    sums across ranks).

    TPU-native semantics — two regimes:

    * **GSPMD (jit / the fleet path)**: the batch dim is *sharded*, not
      per-replica, so ``jnp.mean`` over it already IS the global-batch mean
      (XLA inserts the cross-chip reduction).  No collective is emitted
      here — the sync the reference needed NCCL for is the compiler's job.
    * **shard_map (manual code)**: each program instance sees its local
      shard, so the partial moments are ``lax.pmean``-ed over whichever
      data axes are bound (default: ``data``/``sharding``; override with
      ``axis_name=`` for custom meshes).
    """

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None, axis_name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, None, name)
        self.axis_name = axis_name

    def _sync_axes(self):
        """Mapped axes to reduce over: the bound subset of the defaults, or
        the user's explicit axis_name (which must be bound)."""
        if self.axis_name is None:
            candidates = ("data", "sharding")
            explicit = False
        else:
            candidates = ((self.axis_name,) if isinstance(self.axis_name, str)
                          else tuple(self.axis_name))
            explicit = True
        bound = []
        for a in candidates:
            try:
                jax.lax.axis_size(a)
                bound.append(a)
            except NameError:
                if explicit:
                    from ..framework.errors import InvalidArgumentError

                    raise InvalidArgumentError(
                        f"SyncBatchNorm(axis_name={self.axis_name!r}): axis "
                        f"{a!r} is not bound here — it only names shard_map "
                        f"axes; under plain jit the batch mean is already "
                        f"global (leave axis_name unset)")
        return tuple(bound)

    def forward(self, x):
        x = jnp.asarray(x)
        if not self.training:
            return super().forward(x)
        ch_axis = x.ndim - 1 if self.data_format in ("NHWC", "NLC", "NDHWC") else 1
        axes = tuple(i for i in range(x.ndim) if i != ch_axis)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        meansq = jnp.mean(jnp.square(xf), axis=axes)
        sync = self._sync_axes()
        if sync:
            mean = jax.lax.pmean(mean, sync)
            meansq = jax.lax.pmean(meansq, sync)
        var = meansq - jnp.square(mean)
        new_mean = self.momentum * self._mean.value + (1 - self.momentum) * mean
        new_var = self.momentum * self._variance.value + (1 - self.momentum) * var
        self._mean.value = new_mean
        self._variance.value = new_var
        shape = [1] * x.ndim
        shape[ch_axis] = x.shape[ch_axis]
        inv = jax.lax.rsqrt(var + self.epsilon)
        out = (xf - mean.reshape(shape)) * inv.reshape(shape)
        if self.weight is not None:
            out = out * self.weight.value.reshape(shape)
        if self.bias is not None:
            out = out + self.bias.value.reshape(shape)
        return out.astype(x.dtype)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Parity: paddle.nn.SyncBatchNorm.convert_sync_batchnorm."""
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = cls(layer.num_features, layer.momentum, layer.epsilon,
                      data_format=layer.data_format)
            new.set_state_dict(layer.state_dict())
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    """Parity: paddle.nn.LayerNorm (ref: operators/layer_norm_op.cu)."""

    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(self.normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape,
                            self.weight.value if self.weight is not None else None,
                            self.bias.value if self.bias is not None else None,
                            self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                (num_channels,), attr=weight_attr, default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter((num_channels,), attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon,
                            self.weight.value if self.weight is not None else None,
                            self.bias.value if self.bias is not None else None,
                            self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight.value if self.weight is not None else None,
                               bias=self.bias.value if self.bias is not None else None,
                               eps=self.epsilon, data_format=self.data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Parity: paddle.nn.SpectralNorm (ref: operators/spectral_norm_op.cc) —
    power-iteration estimate of the largest singular value."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.register_buffer("weight_u", jax.random.normal(
            jax.random.PRNGKey(0), (h,), jnp.float32), persistable=False)
        self.register_buffer("weight_v", jax.random.normal(
            jax.random.PRNGKey(1), (w,), jnp.float32), persistable=False)

    def forward(self, weight):
        weight = jnp.asarray(weight)
        mat = jnp.moveaxis(weight, self.dim, 0).reshape(weight.shape[self.dim], -1)
        u, v = self.weight_u.value, self.weight_v.value
        for _ in range(self.power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        sigma = u @ mat @ v
        self.weight_u.value = jax.lax.stop_gradient(u)
        self.weight_v.value = jax.lax.stop_gradient(v)
        return weight / sigma

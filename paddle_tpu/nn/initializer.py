"""Weight initializers + ParamAttr.

Parity surface: paddle.nn.initializer / paddle.ParamAttr
(reference: python/paddle/fluid/initializer.py — ConstantInitializer,
UniformInitializer, NormalInitializer, TruncatedNormal, Xavier, MSRA
(= Kaiming), Bilinear, NumpyArrayInitializer; python/paddle/fluid/param_attr.py).

Initializers are pure callables ``init(shape, dtype, key) -> jax.Array`` —
no init-op graph insertion as in the reference; values materialize directly.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as _dt
from ..framework.random import split_key

__all__ = [
    "Initializer", "Constant", "Uniform", "Normal", "TruncatedNormal",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Bilinear", "Dirac", "Orthogonal", "calculate_gain", "ParamAttr",
]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle Linear weight layout: (in_features, out_features)
        return shape[0], shape[1]
    # conv kernels (paddle layout OIHW): receptive = prod(spatial)
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


class Initializer:
    def __call__(self, shape, dtype=None, key=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None, key=None):
        return jnp.full(tuple(shape), self.value, dtype=_dt.convert_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None, key=None):
        return jax.random.uniform(split_key(key), tuple(shape),
                                  dtype=_dt.convert_dtype(dtype),
                                  minval=self.low, maxval=self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None, key=None):
        d = _dt.convert_dtype(dtype)
        return jax.random.normal(split_key(key), tuple(shape), dtype=d) * self.std + self.mean


class TruncatedNormal(Initializer):
    """Truncated at ±2σ, matching the reference's TruncatedNormalInitializer."""

    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None, key=None):
        d = _dt.convert_dtype(dtype)
        z = jax.random.truncated_normal(split_key(key), -2.0, 2.0, tuple(shape), dtype=d)
        return z * self.std + self.mean


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None, key=None):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        d = _dt.convert_dtype(dtype)
        return jax.random.normal(split_key(key), tuple(shape), dtype=d) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None, key=None):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        d = _dt.convert_dtype(dtype)
        return jax.random.uniform(split_key(key), tuple(shape), dtype=d,
                                  minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    """Parity: MSRAInitializer (fluid/initializer.py) / paddle.nn.initializer.KaimingNormal."""

    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None, key=None):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        d = _dt.convert_dtype(dtype)
        return jax.random.normal(split_key(key), tuple(shape), dtype=d) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None, key=None):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        d = _dt.convert_dtype(dtype)
        return jax.random.uniform(split_key(key), tuple(shape), dtype=d,
                                  minval=-limit, maxval=limit)


class Assign(Initializer):
    """Parity: NumpyArrayInitializer."""

    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, shape, dtype=None, key=None):
        v = jnp.asarray(self.value, dtype=_dt.convert_dtype(dtype))
        if tuple(v.shape) != tuple(shape):
            v = jnp.reshape(v, tuple(shape))
        return v


class Bilinear(Initializer):
    """Bilinear upsampling kernel for ConvTranspose (ref: BilinearInitializer)."""

    def __call__(self, shape, dtype=None, key=None):
        C_out, C_in, *spatial = shape
        weight = np.zeros(tuple(shape), dtype=np.float64)
        k = spatial[0]
        factor = (k + 1) // 2
        center = factor - 1.0 if k % 2 == 1 else factor - 0.5
        og = np.ogrid[tuple(np.s_[:s] for s in spatial)]
        filt = np.ones([1] * len(spatial))
        for g in og:
            filt = filt * (1 - np.abs(g - center) / factor)
        for i in range(min(C_out, C_in)):
            weight[i, i % C_in] = filt
        return jnp.asarray(weight, dtype=_dt.convert_dtype(dtype))


class Dirac(Initializer):
    def __call__(self, shape, dtype=None, key=None):
        C_out, C_in, *spatial = shape
        w = np.zeros(tuple(shape), dtype=np.float64)
        centers = tuple(s // 2 for s in spatial)
        for i in range(min(C_out, C_in)):
            w[(i, i) + centers] = 1.0
        return jnp.asarray(w, dtype=_dt.convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None, key=None):
        d = _dt.convert_dtype(dtype)
        return jax.nn.initializers.orthogonal(scale=self.gain)(split_key(key), tuple(shape), d)


class ParamAttr:
    """Parity: paddle.ParamAttr (python/paddle/fluid/param_attr.py).

    ``learning_rate`` and ``regularizer`` are honored by the optimizer layer
    (per-parameter lr scaling / weight decay), ``trainable`` by the Layer.
    """

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

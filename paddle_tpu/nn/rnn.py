"""Recurrent layers: SimpleRNN / LSTM / GRU (+cells).

Parity surface: paddle.nn.{SimpleRNN,LSTM,GRU,RNNCellBase,...}
(reference: python/paddle/nn/layer/rnn.py; kernels operators/rnn_op /
cudnn_lstm_op.cu, math/gru_compute, lstm_compute).

TPU-native design: the time loop is a single ``lax.scan`` per layer &
direction — XLA compiles it into one fused loop (the cuDNN-RNN equivalent);
gate matmuls are batched into one (4*hidden) MXU matmul per step, the same
packing trick cuDNN uses.  Variable-length sequences use ``sequence_length``
masks (dense padding policy, SURVEY §5 LoD note).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from . import functional as F
from . import initializer as I
from .layer_base import Layer

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN", "RNNBase",
    "split_states", "concat_states",
    "SimpleRNN", "LSTM", "GRU",
]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_size, dtype="float32"):
        raise NotImplementedError


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter((hidden_size, input_size),
                                               attr=weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size),
                                               attr=weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter((hidden_size,), attr=bias_ih_attr,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter((hidden_size,), attr=bias_hh_attr,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = jnp.zeros((inputs.shape[0], self.hidden_size), jnp.asarray(inputs).dtype)
        pre = (jnp.asarray(inputs) @ self.weight_ih.value.T + self.bias_ih.value
               + states @ self.weight_hh.value.T + self.bias_hh.value)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        h = act(pre)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    """Gate order i, f, c(g), o — matches the reference
    (operators/math/detail/lstm_kernel.h)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size),
                                               attr=weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size),
                                               attr=weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter((4 * hidden_size,), attr=bias_ih_attr,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter((4 * hidden_size,), attr=bias_hh_attr,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        x = jnp.asarray(inputs)
        if states is None:
            h = jnp.zeros((x.shape[0], self.hidden_size), x.dtype)
            c = jnp.zeros((x.shape[0], self.hidden_size), x.dtype)
        else:
            h, c = states
        gates = (x @ self.weight_ih.value.T + self.bias_ih.value
                 + h @ self.weight_hh.value.T + self.bias_hh.value)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        new_c = f * c + i * g
        new_h = o * jnp.tanh(new_c)
        return new_h, (new_h, new_c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    """Gate order r(reset), z(update), c(candidate) — paddle convention."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size),
                                               attr=weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size),
                                               attr=weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter((3 * hidden_size,), attr=bias_ih_attr,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter((3 * hidden_size,), attr=bias_hh_attr,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        x = jnp.asarray(inputs)
        if states is None:
            states = jnp.zeros((x.shape[0], self.hidden_size), x.dtype)
        h = states
        xg = x @ self.weight_ih.value.T + self.bias_ih.value
        hg = h @ self.weight_hh.value.T + self.bias_hh.value
        xr, xz, xc = jnp.split(xg, 3, axis=-1)
        hr, hz, hc = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        c = jnp.tanh(xc + r * hc)
        new_h = (1 - z) * c + z * h
        return new_h, new_h

    @property
    def state_shape(self):
        return (self.hidden_size,)


def _scan_rnn(cell, params_fn, inputs, init_state, reverse=False, seq_lens=None):
    """Run a cell over time via lax.scan. inputs: (T, B, I)."""

    def step(state, xt_t):
        xt, t = xt_t
        out, new_state = cell(xt, state)
        if seq_lens is not None:
            valid = (t < seq_lens)[:, None]
            new_state = jax.tree_util.tree_map(
                lambda n, o: jnp.where(valid, n, o), new_state, state)
            out = jnp.where(valid, out, jnp.zeros_like(out))
        return new_state, out

    T = inputs.shape[0]
    ts = jnp.arange(T - 1, -1, -1) if reverse else jnp.arange(T)
    xs = jnp.flip(inputs, 0) if reverse else inputs
    final, outs = jax.lax.scan(step, init_state, (xs, ts))
    if reverse:
        outs = jnp.flip(outs, 0)
    return outs, final


class RNN(Layer):
    """Generic wrapper running a cell over a sequence (paddle.nn.RNN parity)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = jnp.asarray(inputs)
        if not self.time_major:
            x = jnp.swapaxes(x, 0, 1)
        if initial_states is None:
            batch = x.shape[1]
            zeros = jnp.zeros((batch, self.cell.hidden_size), x.dtype)
            initial_states = (zeros, zeros) if isinstance(self.cell, LSTMCell) else zeros
        seq_lens = jnp.asarray(sequence_length) if sequence_length is not None else None
        outs, final = _scan_rnn(self.cell, None, x, initial_states,
                                reverse=self.is_reverse, seq_lens=seq_lens)
        if not self.time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return outs, final


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        fw = RNN(self.cell_fw, False, self.time_major)
        bw = RNN(self.cell_bw, True, self.time_major)
        o1, s1 = fw(inputs, None if initial_states is None else initial_states[0], sequence_length)
        o2, s2 = bw(inputs, None if initial_states is None else initial_states[1], sequence_length)
        return jnp.concatenate([o1, o2], axis=-1), (s1, s2)


#: mode string → cell class (reference nn/layer/rnn.py RNNBase modes)
_RNN_MODES = {
    "LSTM": LSTMCell,
    "GRU": GRUCell,
    "RNN_TANH": SimpleRNNCell,
    "RNN_RELU": SimpleRNNCell,
}


class RNNBase(Layer):
    """Shared multi-layer/bidirectional RNN driver (reference:
    nn/layer/rnn.py RNNBase) — the first argument selects the cell mode;
    SimpleRNN/LSTM/GRU subclass this with their mode pinned."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation=None, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if mode not in _RNN_MODES:
            from ..framework.errors import InvalidArgumentError

            raise InvalidArgumentError(
                f"mode must be one of {sorted(_RNN_MODES)}, got {mode!r}")
        self._mode = mode
        self._cell_cls = _RNN_MODES[mode]
        if activation is None:
            activation = "relu" if mode == "RNN_RELU" else "tanh"
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirectional else 1
        kw = {}
        if self._cell_cls is SimpleRNNCell:
            kw["activation"] = activation
        for layer in range(num_layers):
            in_size = input_size if layer == 0 else hidden_size * num_dirs
            for d in range(num_dirs):
                cell = self._cell_cls(in_size, hidden_size,
                                      weight_ih_attr=weight_ih_attr,
                                      weight_hh_attr=weight_hh_attr,
                                      bias_ih_attr=bias_ih_attr,
                                      bias_hh_attr=bias_hh_attr, **kw)
                self.add_sublayer(f"cell_{layer}_{d}", cell)

    def _cells(self):
        num_dirs = 2 if self.bidirectional else 1
        return [[self._sub_layers[f"cell_{l}_{d}"] for d in range(num_dirs)]
                for l in range(self.num_layers)]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = jnp.asarray(inputs)
        if not self.time_major:
            x = jnp.swapaxes(x, 0, 1)  # (T, B, I)
        batch = x.shape[1]
        num_dirs = 2 if self.bidirectional else 1
        is_lstm = self._cell_cls is LSTMCell
        seq_lens = jnp.asarray(sequence_length) if sequence_length is not None else None

        def init_state(layer, d):
            idx = layer * num_dirs + d
            if initial_states is None:
                z = jnp.zeros((batch, self.hidden_size), x.dtype)
                return (z, z) if is_lstm else z
            if is_lstm:
                h0, c0 = initial_states
                return (jnp.asarray(h0)[idx], jnp.asarray(c0)[idx])
            return jnp.asarray(initial_states)[idx]

        out = x
        final_h, final_c = [], []
        for layer, cells in enumerate(self._cells()):
            outs_dirs = []
            for d, cell in enumerate(cells):
                o, f = _scan_rnn(cell, None, out, init_state(layer, d),
                                 reverse=(d == 1), seq_lens=seq_lens)
                outs_dirs.append(o)
                if is_lstm:
                    final_h.append(f[0])
                    final_c.append(f[1])
                else:
                    final_h.append(f)
            out = outs_dirs[0] if len(outs_dirs) == 1 else jnp.concatenate(outs_dirs, -1)
            if self.dropout > 0 and layer < self.num_layers - 1:
                out = F.dropout(out, p=self.dropout, training=self.training)
        if not self.time_major:
            out = jnp.swapaxes(out, 0, 1)
        h = jnp.stack(final_h, 0)
        if is_lstm:
            c = jnp.stack(final_c, 0)
            return out, (h, c)
        return out, h


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout,
                         activation=activation, **kw)


class LSTM(RNNBase):
    """Parity: paddle.nn.LSTM (ref: operators/cudnn_lstm_op.cu → lax.scan)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


def split_states(states, bidirectional=False, state_components=1):
    """Split concatenated [L*D, N, C] RNN-network states into per-cell
    states (reference: nn/layer/rnn.py:49)."""
    if state_components == 1:
        parts = [states[i] for i in range(states.shape[0])]
        if not bidirectional:
            return parts
        return list(zip(parts[::2], parts[1::2]))
    assert len(states) == state_components
    comps = tuple([item[i] for i in range(item.shape[0])] for item in states)
    zipped = list(zip(*comps))
    if not bidirectional:
        return zipped
    return list(zip(zipped[::2], zipped[1::2]))


def concat_states(states, bidirectional=False, state_components=1):
    """Inverse of split_states: nested per-cell states → [L*D, N, C]
    (reference: nn/layer/rnn.py:102)."""
    flat = jax.tree_util.tree_leaves(states)
    if state_components == 1:
        return jnp.stack(flat)
    comps = [flat[i::state_components] for i in range(state_components)]
    return tuple(jnp.stack(c) for c in comps)

"""Linear-chain CRF ops.

Parity: operators/linear_chain_crf_op.{cc,h} (forward-algorithm
log-likelihood) and operators/crf_decoding_op.h (Viterbi decode), the ops
behind the label_semantic_roles book test (tests/book/
test_label_semantic_roles.py).

Conventions kept from the reference (linear_chain_crf_op.cc:103-107):
``transition`` is ``[(D+2), D]`` — row 0 holds the start weights, row 1
the stop weights, rows 2.. the D×D transition matrix.  The reference's
kernel is a per-sequence C++ loop over LoD slices with L1-normalized
alphas; here sequences are dense-padded ``[B, T, D]`` and the forward /
Viterbi recursions are ``lax.scan`` over time in log space — batch
parallelism comes from the scan body's vectorized ops, autodiff replaces
the hand-written gradient kernel (LinearChainCRFGradOpKernel).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.special import logsumexp

__all__ = ["linear_chain_crf", "crf_decoding", "viterbi_decode"]


def _split(transition):
    t = jnp.asarray(transition, jnp.float32)
    return t[0], t[1], t[2:]  # start [D], stop [D], trans [D, D]


def _lengths_mask(B, T, length):
    if length is None:
        return jnp.ones((B, T), bool), jnp.full((B,), T, jnp.int32)
    length = jnp.asarray(length, jnp.int32).reshape(B)
    return jnp.arange(T, dtype=jnp.int32)[None, :] < length[:, None], length


def linear_chain_crf(emission, transition, label,
                     length=None):
    """Negative log-likelihood of ``label`` paths under a linear-chain CRF.

    emission ``[B, T, D]``, transition ``[(D+2), D]``, label ``[B, T]``
    int, length ``[B]`` (None → all full).  Returns ``[B, 1]`` — the
    reference's LogLikelihood output, used directly as a cost.
    """
    e = jnp.asarray(emission, jnp.float32)
    B, T, D = e.shape
    y = jnp.asarray(label, jnp.int32).reshape(B, T)
    start, stop, trans = _split(transition)
    mask, length = _lengths_mask(B, T, length)

    # -- partition function: forward algorithm over time ---------------------
    alpha = start[None, :] + e[:, 0]  # [B, D]

    def fwd(alpha, xs):
        e_t, m_t = xs  # [B, D], [B]
        nxt = logsumexp(alpha[:, :, None] + trans[None], axis=1) + e_t
        return jnp.where(m_t[:, None], nxt, alpha), None

    if T > 1:
        alpha, _ = lax.scan(
            fwd, alpha,
            (e[:, 1:].transpose(1, 0, 2), mask[:, 1:].T))
    log_z = logsumexp(alpha + stop[None, :], axis=-1)  # [B]

    # -- gold-path score -----------------------------------------------------
    e_path = jnp.take_along_axis(e, y[:, :, None], axis=2)[:, :, 0]  # [B,T]
    score = start[y[:, 0]] + e_path[:, 0]
    if T > 1:
        step_scores = trans[y[:, :-1], y[:, 1:]] + e_path[:, 1:]  # [B,T-1]
        score = score + jnp.where(mask[:, 1:], step_scores, 0.0).sum(axis=1)
    y_last = jnp.take_along_axis(y, (length - 1)[:, None], axis=1)[:, 0]
    score = score + stop[y_last]

    return (log_z - score)[:, None]


def viterbi_decode(emission, transition, length=None):
    """Highest-scoring tag path.  Returns ``(path [B, T] i32, score [B])``;
    positions beyond ``length`` hold 0."""
    e = jnp.asarray(emission, jnp.float32)
    B, T, D = e.shape
    start, stop, trans = _split(transition)
    mask, length = _lengths_mask(B, T, length)

    delta = start[None, :] + e[:, 0]  # [B, D]

    def step(delta, xs):
        e_t, m_t = xs
        scores = delta[:, :, None] + trans[None]        # [B, D_prev, D]
        back = jnp.argmax(scores, axis=1).astype(jnp.int32)  # [B, D]
        nxt = jnp.max(scores, axis=1) + e_t
        nxt = jnp.where(m_t[:, None], nxt, delta)
        # padded steps point to themselves so the backtrace passes through
        back = jnp.where(m_t[:, None],
                         back, jnp.arange(D, dtype=jnp.int32)[None, :])
        return nxt, back

    if T > 1:
        delta, backs = lax.scan(
            step, delta, (e[:, 1:].transpose(1, 0, 2), mask[:, 1:].T))
    else:
        backs = jnp.zeros((0, B, D), jnp.int32)

    final = delta + stop[None, :]
    best_score = jnp.max(final, axis=-1)
    best_last = jnp.argmax(final, axis=-1).astype(jnp.int32)  # [B]

    def trace(tag, back_t):
        prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first, rest = lax.scan(trace, best_last, backs, reverse=True)
    # rest[t-1] holds the tag at step t (reverse scan stores outputs at the
    # matching xs index); the final carry is the tag at step 0
    path = (jnp.concatenate([first[None], rest], axis=0)
            if T > 1 else first[None])
    path = path.T  # [B, T]
    return jnp.where(mask, path, 0).astype(jnp.int32), best_score


def crf_decoding(emission, transition, label=None, length=None):
    """Reference crf_decoding_op.h: Viterbi path, or — when ``label`` is
    given — a per-position 0/1 tensor marking where the best path and the
    label AGREE (crf_decoding_op.h:70 ``label == path ? 1 : 0``; positions
    beyond ``length`` are 0), so ``output.sum()/num_tokens`` is tagging
    accuracy."""
    path, _ = viterbi_decode(emission, transition, length)
    if label is None:
        return path
    B, T = path.shape
    y = jnp.asarray(label, jnp.int32).reshape(B, T)
    mask, _ = _lengths_mask(B, T, length)
    return jnp.where(mask, (path == y).astype(jnp.int64), 0)

"""Activation functions.

Parity surface: paddle.nn.functional activations (reference:
paddle/fluid/operators/activation_op.cc — ~35 registered activations).
Each lowers to a couple of XLA elementwise HLOs that fuse into the
surrounding computation; on TPU these run on the VPU fused with the matmul
epilogue, so there is no standalone "activation kernel" to optimize.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import dtype as _dt

__all__ = [
    "relu", "relu6", "relu_", "elu", "selu", "celu", "gelu", "silu", "swish",
    "leaky_relu", "prelu", "rrelu", "hardshrink", "hardsigmoid", "hardswish",
    "hardtanh", "log_sigmoid", "log_softmax", "softmax", "softmax_",
    "maxout", "mish", "softplus", "softshrink", "softsign", "tanhshrink",
    "thresholded_relu", "glu", "gumbel_softmax", "sigmoid", "tanh",
]


def _f(x):
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(_dt.get_default_dtype())
    return x


def relu(x, name=None):
    return jax.nn.relu(jnp.asarray(x))


relu_ = relu


def relu6(x, name=None):
    return jax.nn.relu6(jnp.asarray(x))


def elu(x, alpha=1.0, name=None):
    return jax.nn.elu(_f(x), alpha=alpha)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    x = _f(x)
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def celu(x, alpha=1.0, name=None):
    return jax.nn.celu(_f(x), alpha=alpha)


def gelu(x, approximate=False, name=None):
    return jax.nn.gelu(_f(x), approximate=approximate)


def silu(x, name=None):
    return jax.nn.silu(_f(x))


def swish(x, name=None):
    return jax.nn.silu(_f(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return jax.nn.leaky_relu(_f(x), negative_slope=negative_slope)


def prelu(x, weight, data_format="NCHW", name=None):
    x = _f(x)
    w = jnp.asarray(weight, x.dtype)
    if w.size > 1:
        # broadcast per-channel weight along the channel axis
        axis = 1 if data_format == "NCHW" else x.ndim - 1
        shape = [1] * x.ndim
        shape[axis] = w.size
        w = w.reshape(shape)
    return jnp.where(x > 0, x, w * x)


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=True, name=None, key=None):
    x = _f(x)
    if training:
        from ..layer_base import current_rng_key

        k = key if key is not None else current_rng_key()
        a = jax.random.uniform(k, x.shape, dtype=x.dtype,
                               minval=lower, maxval=upper)
    else:
        a = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, a * x)


def hardshrink(x, threshold=0.5, name=None):
    x = _f(x)
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    x = _f(x)
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def hardswish(x, name=None):
    x = _f(x)
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return jnp.clip(_f(x), min, max)


def log_sigmoid(x, name=None):
    return jax.nn.log_sigmoid(_f(x))


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = _f(x)
    if dtype is not None:
        x = x.astype(_dt.convert_dtype(dtype))
    return jax.nn.log_softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    x = _f(x)
    if dtype is not None:
        x = x.astype(_dt.convert_dtype(dtype))
    return jax.nn.softmax(x, axis=axis)


softmax_ = softmax


def maxout(x, groups, axis=1, name=None):
    """Parity: operators/maxout_op.cc."""
    x = jnp.asarray(x)
    c = x.shape[axis]
    nd = x.ndim
    axis = axis % nd
    new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def mish(x, name=None):
    x = _f(x)
    return x * jnp.tanh(jax.nn.softplus(x))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    x = _f(x)
    scaled = beta * x
    return jnp.where(scaled > threshold, x, jax.nn.softplus(scaled) / beta)


def softshrink(x, threshold=0.5, name=None):
    x = _f(x)
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


def softsign(x, name=None):
    x = _f(x)
    return x / (1.0 + jnp.abs(x))


def tanhshrink(x, name=None):
    x = _f(x)
    return x - jnp.tanh(x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    x = _f(x)
    return jnp.where(x > threshold, x, value)


def glu(x, axis=-1, name=None):
    x = _f(x)
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None, key=None):
    from ..layer_base import current_rng_key

    x = _f(x)
    k = key if key is not None else current_rng_key()
    g = jax.random.gumbel(k, x.shape, dtype=x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
        y = y_hard - jax.lax.stop_gradient(y) + y  # straight-through estimator
    return y


def sigmoid(x, name=None):
    return jax.nn.sigmoid(_f(x))


def tanh(x, name=None):
    return jnp.tanh(_f(x))

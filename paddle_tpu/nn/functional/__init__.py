"""paddle_tpu.nn.functional — functional NN ops (paddle.nn.functional parity)."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from .crf import *  # noqa: F401,F403
from .extension import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .detection_targets import *  # noqa: F401,F403
from .roi_extra import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .deform_conv import *  # noqa: F401,F403
from ...tensor.manipulation import pad  # noqa: F401  # paddle exposes pad under nn.functional too
from ...tensor.creation import assign  # noqa: F401  # ref nn/functional re-exports assign

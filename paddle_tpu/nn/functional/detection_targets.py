"""Two-stage detector training target ops (Faster/Mask-RCNN, RetinaNet).

Capability parity (reference):
  rpn_target_assign        python/paddle/fluid/layers/detection.py:310 over
                           operators/detection/rpn_target_assign_op.cc
  retinanet_target_assign  detection.py:69, same kernel's GetAllFgBgGt
  generate_proposal_labels detection.py:2590 over
                           operators/detection/generate_proposal_labels_op.cc
  generate_mask_labels     detection.py:2742 over
                           operators/detection/generate_mask_labels_op.cc

TPU-native design: the reference kernels are CPU loops with dynamic-length
outputs (LoD) and reservoir sampling from a nondeterministic engine.  Here
every op is a dense, vmapped, jit-able computation with FIXED capacities:

  * ragged per-image ground truth arrives as zero-padded ``[N, G, ...]``
    tensors plus a ``gt_num [N]`` count (the dense stand-in for LoD used
    across this package);
  * subsampling quotas are filled by top-k over PRNG-keyed candidate scores
    (uniform over candidate sets, like reservoir sampling) — bit-identical
    streams with the reference's ``std::minstd_rand`` are impossible (it
    seeds from ``std::random_device``, so even two reference runs differ);
    with ``use_random=False`` both implementations keep the first k
    candidates in index order and agree exactly;
  * variable-length outputs become capacity-sized tensors with padding rows
    marked by label ``-1`` (classification) and zero weights (regression),
    so downstream losses mask them with ``ignore_index=-1`` / the returned
    weights instead of dynamic shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.errors import InvalidArgumentError
from .detection import iou_similarity

__all__ = [
    "rpn_target_assign", "retinanet_target_assign",
    "generate_proposal_labels", "generate_mask_labels",
    "rasterize_polygon",
]

_EPS_TIE = 1e-5  # ScoreAssign epsilon (rpn_target_assign_op.cc:180)


def _require_key(key, use_random, name):
    del name
    if key is not None:
        return key
    if not use_random:
        return jax.random.PRNGKey(0)  # unused: sampling is first-k
    from ...framework import random as _random

    return _random.split_key()


def _rank_desc(score):
    """Rank of each element in a descending sort (0 = largest)."""
    order = jnp.argsort(-score)
    return jnp.zeros_like(order).at[order].set(jnp.arange(score.shape[0]))


def _candidate_scores(mask, key, use_random):
    """Random (or index-order) priority scores over a candidate mask;
    non-candidates get -inf.  Taking the top-k of these is a uniform
    k-subset of the candidates — ReservoirSampling's distribution; with
    use_random=False the first k candidates win, exactly as the
    reference's ``resize(num)``."""
    m = mask.shape[0]
    if use_random:
        score = jax.random.uniform(key, (m,))
    else:
        score = -jnp.arange(m, dtype=jnp.float32)  # earlier index wins
    return jnp.where(mask, score, -jnp.inf)


def _sample_k(mask, k, key, use_random):
    """Uniformly choose ≤k elements of a boolean candidate mask."""
    k = min(int(k), mask.shape[0])
    if k <= 0:
        return jnp.zeros_like(mask)
    score = _candidate_scores(mask, key, use_random)
    _, idx = jax.lax.top_k(score, k)
    sel = jnp.zeros_like(mask).at[idx].set(True)
    return sel & mask  # drop -inf winners when candidates < k


def _sample_dynamic(mask, k_dynamic, key, use_random):
    """Like :func:`_sample_k` but with a traced (data-dependent) quota:
    keep the candidates whose random rank is below ``k_dynamic``."""
    score = _candidate_scores(mask, key, use_random)
    return mask & (_rank_desc(score) < k_dynamic)


def _compact_indices(mask, capacity, priority=None):
    """Pack the True positions of ``mask [M]`` into ``capacity`` slots.

    Returns (src [capacity] int32 — original index per slot, -1 padding,
    valid [capacity] bool).  Order: ascending ``priority`` (default: index
    order).  The standard dense compaction used across the detection ops.
    """
    m = mask.shape[0]
    if priority is None:
        order = jnp.arange(m)
    else:
        # stable order by (priority, index): scale priority into the gaps
        order = priority * m + jnp.arange(m)
    key = jnp.where(mask, order, jnp.iinfo(jnp.int32).max)
    rank = jnp.argsort(key)  # selected first, by priority then index
    src = rank[:capacity].astype(jnp.int32)
    valid = jnp.arange(capacity) < mask.sum()
    return jnp.where(valid, src, -1), valid


def _box_to_delta(ex, gt, weights=None):
    """BoxToDelta (bbox_util.h:56): +1-pixel center-size encoding of gt
    against ex boxes; optional per-coordinate weight division."""
    ew = ex[..., 2] - ex[..., 0] + 1.0
    eh = ex[..., 3] - ex[..., 1] + 1.0
    ex_x = ex[..., 0] + 0.5 * ew
    ex_y = ex[..., 1] + 0.5 * eh
    gw = gt[..., 2] - gt[..., 0] + 1.0
    gh = gt[..., 3] - gt[..., 1] + 1.0
    gx = gt[..., 0] + 0.5 * gw
    gy = gt[..., 1] + 0.5 * gh
    d = jnp.stack([(gx - ex_x) / ew, (gy - ex_y) / eh,
                   jnp.log(gw / ew), jnp.log(gh / eh)], axis=-1)
    if weights is not None:
        d = d / jnp.asarray(weights, d.dtype)
    return d


def _rpn_assign_one(anchors, gt, is_crowd, gt_count, im_info, cfg, key):
    """Per-image ScoreAssign + sampling (rpn_target_assign_op.cc:172-275).

    Returns per-capacity-slot gather indices/targets; see caller.
    """
    (batch_size, straddle, pos_ov, neg_ov, fg_frac, use_random) = cfg
    M = anchors.shape[0]
    G = gt.shape[0]
    im_h, im_w, im_scale = im_info[0], im_info[1], im_info[2]

    if straddle >= 0:
        inside = ((anchors[:, 0] >= -straddle) & (anchors[:, 1] >= -straddle)
                  & (anchors[:, 2] < im_w + straddle)
                  & (anchors[:, 3] < im_h + straddle))
    else:
        inside = jnp.ones((M,), bool)

    valid_gt = (jnp.arange(G) < gt_count) & (is_crowd == 0)
    gt_scaled = gt * im_scale

    iou = iou_similarity(anchors, gt_scaled, box_normalized=False)  # [M, G]
    pair_ok = inside[:, None] & valid_gt[None, :]
    iou = jnp.where(pair_ok, iou, -1.0)

    a2g_max = jnp.max(iou, axis=1)            # [M]; -1 where outside
    a2g_arg = jnp.argmax(iou, axis=1)         # [M]
    g2a_max = jnp.max(iou, axis=0)            # [G]; -1 for invalid gts

    tie = pair_ok & (jnp.abs(iou - g2a_max[None, :]) < _EPS_TIE)
    fg_cand = inside & (tie.any(axis=1) | (a2g_max >= pos_ov))
    bg_cand = inside & (a2g_max < neg_ov)

    key_fg, key_bg = jax.random.split(key)
    if batch_size < 0:
        # RetinaNet call shape (kernel passes batch=-1, fraction=-1):
        # every candidate trains, no subsampling
        fg_sel = fg_cand
        bg_sel = bg_cand
        fg_fake_num = fg_sel.sum()
        F_cap = S_cap = M
    else:
        fg_quota = int(fg_frac * batch_size)
        fg_sel = _sample_k(fg_cand, fg_quota, key_fg, use_random)
        fg_fake_num = fg_sel.sum()
        # bg quota is dynamic: batch_size - sampled fg (op.cc:226-233)
        bg_sel = _sample_dynamic(bg_cand, batch_size - fg_fake_num,
                                 key_bg, use_random)
        F_cap = max(fg_quota, 1)
        S_cap = batch_size

    # the reference's two-directions overwrite: a sampled bg that was also
    # sampled fg flips to label 0 and its loc slot becomes a zero-weight
    # "fake" pointing at an arbitrary fg anchor (weight 0 ⇒ no gradient)
    fake = fg_sel & bg_sel
    real_fg = fg_sel & ~bg_sel
    # loc slots: fakes first (priority 0), then real fg (priority 1)
    loc_src, loc_valid = _compact_indices(
        fake | real_fg, F_cap, priority=jnp.where(fake, 0, 1))
    fg_first = jnp.argmax(fg_sel)  # substitute anchor for fake slots
    is_fake_slot = loc_valid & fake[jnp.clip(loc_src, 0, M - 1)]
    loc_anchor_idx = jnp.where(is_fake_slot, fg_first,
                               jnp.clip(loc_src, 0, M - 1))
    gt_idx = a2g_arg[loc_anchor_idx]
    tgt_bbox = _box_to_delta(anchors[loc_anchor_idx], gt_scaled[gt_idx])
    inside_w = (loc_valid & ~is_fake_slot).astype(anchors.dtype)[:, None]
    inside_w = jnp.broadcast_to(inside_w, (F_cap, 4))
    tgt_bbox = jnp.where(loc_valid[:, None], tgt_bbox, 0.0)
    loc_index = jnp.where(loc_valid, loc_anchor_idx, 0).astype(jnp.int32)

    # score slots: real fg (label 1) then bg (label 0)
    score_src, score_valid = _compact_indices(
        real_fg | bg_sel, S_cap, priority=jnp.where(real_fg, 0, 1))
    safe_score = jnp.clip(score_src, 0, M - 1)
    label = jnp.where(real_fg[safe_score], 1, 0)
    label = jnp.where(score_valid, label, -1).astype(jnp.int32)
    score_index = jnp.where(score_valid, safe_score, 0).astype(jnp.int32)

    return (loc_index, loc_valid, tgt_bbox, inside_w,
            score_index, score_valid, label, a2g_arg, real_fg, fg_fake_num)


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info, gt_num=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True, key=None):
    """RPN training target assignment (ref: detection.py:310 over
    rpn_target_assign_op.cc).

    Dense contract: ``bbox_pred [N, M, 4]``, ``cls_logits [N, M, 1]``,
    ``anchor_box [M, 4]``, ``gt_boxes [N, G, 4]`` zero-padded with
    ``gt_num [N]`` valid counts (omitted → all G), ``is_crowd [N, G]``
    int, ``im_info [N, 3]`` (h, w, scale).

    Returns the reference 5-tuple with fixed capacities
    ``F = N*max(int(rpn_fg_fraction*rpn_batch_size_per_im), 1)`` and
    ``S = N*rpn_batch_size_per_im``:
    (predicted_scores ``[S, 1]``, predicted_location ``[F, 4]``,
    target_label ``[S, 1]`` — padding rows are ``-1`` (mask the
    classification loss with ``ignore_index=-1``), target_bbox ``[F, 4]``,
    bbox_inside_weight ``[F, 4]`` — 0 on fake-fg and padding rows).
    """
    bbox_pred = jnp.asarray(bbox_pred)
    cls_logits = jnp.asarray(cls_logits)
    anchors = jnp.asarray(anchor_box)
    gt_boxes = jnp.asarray(gt_boxes)
    if bbox_pred.ndim != 3 or gt_boxes.ndim != 3:
        raise InvalidArgumentError(
            "rpn_target_assign dense contract wants batched bbox_pred "
            f"[N,M,4] and gt_boxes [N,G,4]; got {bbox_pred.shape}, "
            f"{gt_boxes.shape}")
    N, M = bbox_pred.shape[0], anchors.shape[0]
    G = gt_boxes.shape[1]
    is_crowd = jnp.asarray(is_crowd).reshape(N, G)
    im_info = jnp.asarray(im_info, anchors.dtype)
    gt_count = (jnp.full((N,), G, jnp.int32) if gt_num is None
                else jnp.asarray(gt_num, jnp.int32))
    key = _require_key(key, use_random, "rpn_target_assign")
    cfg = (int(rpn_batch_size_per_im), float(rpn_straddle_thresh),
           float(rpn_positive_overlap), float(rpn_negative_overlap),
           float(rpn_fg_fraction), bool(use_random))

    keys = jax.random.split(key, N)
    outs = jax.vmap(
        lambda g, c, n, ii, k: _rpn_assign_one(anchors, g, c, n, ii, cfg, k)
    )(gt_boxes, is_crowd, gt_count, im_info, keys)
    (loc_index, loc_valid, tgt_bbox, inside_w,
     score_index, score_valid, label, _, _, _) = outs

    F_cap = loc_index.shape[1]
    S_cap = score_index.shape[1]
    # unflatten gathers: per-image anchor index + i*M (the reference's
    # "Add anchor offset" step), then gather from the flattened preds
    img_off_loc = (jnp.arange(N)[:, None] * M + loc_index).reshape(-1)
    img_off_score = (jnp.arange(N)[:, None] * M + score_index).reshape(-1)
    pred_loc = bbox_pred.reshape(N * M, 4)[img_off_loc]
    pred_scores = cls_logits.reshape(N * M, -1)[img_off_score][:, :1]
    pred_loc = jnp.where(loc_valid.reshape(-1)[:, None], pred_loc, 0.0)
    pred_scores = jnp.where(score_valid.reshape(-1)[:, None], pred_scores, 0.0)

    return (pred_scores, pred_loc,
            label.reshape(N * S_cap, 1),
            tgt_bbox.reshape(N * F_cap, 4),
            inside_w.reshape(N * F_cap, 4))


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd, im_info,
                            num_classes=1, gt_num=None,
                            positive_overlap=0.5, negative_overlap=0.4,
                            key=None):
    """RetinaNet target assignment (ref: detection.py:69 over
    rpn_target_assign_op.cc GetAllFgBgGt): like the RPN assigner but with
    NO subsampling (every fg/bg anchor trains) and class labels from
    ``gt_labels`` instead of binary objectness.

    Dense contract as :func:`rpn_target_assign` plus ``gt_labels [N, G]``
    int and ``cls_logits [N, M, num_classes]``.  Capacities are ``M`` per
    image (no quota).  Returns (predicted_scores ``[N*M, num_classes]``,
    predicted_location ``[N*M, 4]``, target_label ``[N*M, 1]`` with -1
    padding, target_bbox ``[N*M, 4]``, bbox_inside_weight ``[N*M, 4]``,
    fg_num ``[N, 1]`` — per-image foreground count + 1, the reference's
    focal-loss normalizer).
    """
    bbox_pred = jnp.asarray(bbox_pred)
    cls_logits = jnp.asarray(cls_logits)
    anchors = jnp.asarray(anchor_box)
    gt_boxes = jnp.asarray(gt_boxes)
    N, M = bbox_pred.shape[0], anchors.shape[0]
    G = gt_boxes.shape[1]
    gt_labels = jnp.asarray(gt_labels).reshape(N, G)
    is_crowd = jnp.asarray(is_crowd).reshape(N, G)
    im_info = jnp.asarray(im_info, anchors.dtype)
    gt_count = (jnp.full((N,), G, jnp.int32) if gt_num is None
                else jnp.asarray(gt_num, jnp.int32))
    key = _require_key(key, False, "retinanet_target_assign")

    def one(gt, lbls, crowd, n, ii, k):
        # batch=-1/frac=-1 ⇒ no sampling (kernel's RetinaNet call), so fg =
        # all candidates, bg = all candidates; a tie-fg with iou < neg_ov
        # still flips to bg (the same two-directions overwrite)
        cfg = (-1, -1.0, float(positive_overlap), float(negative_overlap),
               -1.0, False)
        (loc_index, loc_valid, tgt_bbox, inside_w, score_index, score_valid,
         label, a2g_arg, real_fg, fg_fake_num) = _rpn_assign_one(
            anchors, gt, crowd, n, ii, cfg, k)
        # class labels: fg rows take the matched gt's label
        safe = jnp.clip(score_index, 0, M - 1)
        cls = jnp.where(real_fg[safe], lbls[a2g_arg[safe]], 0)
        label = jnp.where(label == 1, cls, label).astype(jnp.int32)
        return (loc_index, loc_valid, tgt_bbox, inside_w, score_index,
                score_valid, label, fg_fake_num)

    keys = jax.random.split(key, N)
    (loc_index, loc_valid, tgt_bbox, inside_w, score_index, score_valid,
     label, fg_fake_num) = jax.vmap(one)(
        gt_boxes, gt_labels, is_crowd, gt_count, im_info, keys)

    img_off_loc = (jnp.arange(N)[:, None] * M + loc_index).reshape(-1)
    img_off_score = (jnp.arange(N)[:, None] * M + score_index).reshape(-1)
    pred_loc = bbox_pred.reshape(N * M, 4)[img_off_loc]
    pred_scores = cls_logits.reshape(N * M, -1)[img_off_score]
    pred_loc = jnp.where(loc_valid.reshape(-1)[:, None], pred_loc, 0.0)
    pred_scores = jnp.where(score_valid.reshape(-1)[:, None], pred_scores, 0.0)
    fg_num = (fg_fake_num + 1).astype(jnp.int32).reshape(N, 1)
    return (pred_scores, pred_loc, label.reshape(-1, 1),
            tgt_bbox.reshape(-1, 4), inside_w.reshape(-1, 4), fg_num)


def _proposal_labels_one(rois, roi_count, gt_cls, crowd, gt, gt_count,
                         im_info, max_ov_in, cfg, key):
    """SampleRoisForOneImage (generate_proposal_labels_op.cc:305-446)."""
    (B, fg_frac, fg_thresh, bg_hi, bg_lo, reg_w, C, use_random,
     cascade, agnostic) = cfg
    R, G = rois.shape[0], gt.shape[0]
    P = G + R
    im_scale = im_info[2]

    rois = rois / im_scale
    valid_gt = jnp.arange(G) < gt_count
    valid_roi = jnp.arange(R) < roi_count
    if cascade:
        # FilterRoIs (op.cc:40): keep rois with positive +1-size and
        # max_overlap < 1 from the previous stage
        keep = ((rois[:, 2] - rois[:, 0] + 1) > 0) \
            & ((rois[:, 3] - rois[:, 1] + 1) > 0) & (max_ov_in < 1.0)
        valid_roi = valid_roi & keep

    boxes = jnp.concatenate([gt, rois], axis=0)        # [P, 4]
    valid_row = jnp.concatenate([valid_gt, valid_roi])
    iou = iou_similarity(boxes, gt, box_normalized=False)  # [P, G]
    iou = jnp.where(valid_row[:, None] & valid_gt[None, :], iou, -1.0)
    max_ov = jnp.max(iou, axis=1)                      # [P]
    # crowd gt rows are forced out of both pools (max = -1)
    row_crowd = jnp.concatenate([(crowd != 0) & valid_gt,
                                 jnp.zeros((R,), bool)])
    max_ov = jnp.where(row_crowd, -1.0, max_ov)

    fg_cand = max_ov >= fg_thresh
    # if/elif in the kernel: an unsampled fg candidate never becomes bg,
    # even when fg_thresh < bg_thresh_hi puts its overlap in the bg band
    bg_cand = ~fg_cand & (max_ov >= bg_lo) & (max_ov < bg_hi)
    # mapped gt: first column within eps of the row max (op.cc:186-193)
    tie = (jnp.abs(max_ov[:, None] - iou) < _EPS_TIE) & valid_gt[None, :]
    mapped_gt = jnp.argmax(tie, axis=1)

    key_fg, key_bg = jax.random.split(key)
    if cascade:
        fg_sel, bg_sel = fg_cand, bg_cand
        cap = P
    else:
        fg_quota = int(B * fg_frac)
        fg_sel = _sample_k(fg_cand, fg_quota, key_fg, use_random)
        bg_sel = _sample_dynamic(bg_cand, B - fg_sel.sum(), key_bg,
                                 use_random)
        cap = B

    # fg rows first, then bg rows
    src, valid = _compact_indices(fg_sel | bg_sel, cap,
                                  priority=jnp.where(fg_sel, 0, 1))
    safe = jnp.clip(src, 0, P - 1)
    is_fg = valid & fg_sel[safe]
    sampled_boxes = boxes[safe]
    g_idx = mapped_gt[safe]
    labels = jnp.where(is_fg, gt_cls[g_idx], 0)
    labels = jnp.where(valid, labels, -1).astype(jnp.int32)
    sampled_max_ov = jnp.where(valid, max_ov[safe], 0.0)

    deltas = _box_to_delta(sampled_boxes, gt[g_idx], reg_w)
    # expand to [cap, 4C] at the class slot (op.cc:415-436)
    slot = jnp.where(is_fg, jnp.where(agnostic, 1, labels), 0)
    onehot = jax.nn.one_hot(slot, C, dtype=deltas.dtype) \
        * is_fg[:, None].astype(deltas.dtype)            # [cap, C]
    bbox_targets = (onehot[:, :, None] * deltas[:, None, :]).reshape(cap,
                                                                     4 * C)
    w = jnp.repeat(onehot, 4, axis=1)                    # [cap, 4C]
    rois_out = jnp.where(valid[:, None], sampled_boxes * im_scale, 0.0)
    return (rois_out, labels, bbox_targets, w, w, sampled_max_ov,
            (fg_sel | bg_sel).sum().astype(jnp.int32))


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, rois_num=None, gt_num=None,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.25, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False,
                             max_overlap=None, return_max_overlap=False,
                             key=None):
    """Sample RoIs and build RCNN-head training targets (ref:
    detection.py:2590 over generate_proposal_labels_op.cc).

    Dense contract: ``rpn_rois [N, R, 4]`` zero-padded with ``rois_num
    [N]`` valid counts, ``gt_classes/is_crowd [N, G]``, ``gt_boxes
    [N, G, 4]`` with ``gt_num [N]``, ``im_info [N, 3]``.  Ground-truth
    boxes join the proposal pool (op.cc:352 ``Concat(gt_boxes, rois)``).

    Capacity per image: ``batch_size_per_im`` (or ``G+R`` when
    ``is_cascade_rcnn`` — no sampling in cascade mode).  Returns
    (rois ``[N*B, 4]``, labels_int32 ``[N*B, 1]`` with -1 padding,
    bbox_targets ``[N*B, 4*class_nums]``, bbox_inside_weights,
    bbox_outside_weights, [max_overlap ``[N*B]``]); classification loss
    should use ``ignore_index=-1`` and the regression loss the weights.
    """
    if class_nums is None:
        raise InvalidArgumentError("class_nums is required")
    rois = jnp.asarray(rpn_rois)
    gt_boxes = jnp.asarray(gt_boxes)
    if rois.ndim != 3 or gt_boxes.ndim != 3:
        raise InvalidArgumentError(
            "generate_proposal_labels dense contract wants rpn_rois "
            f"[N,R,4] and gt_boxes [N,G,4]; got {rois.shape}, "
            f"{gt_boxes.shape}")
    N, R = rois.shape[0], rois.shape[1]
    G = gt_boxes.shape[1]
    gt_classes = jnp.asarray(gt_classes).reshape(N, G)
    is_crowd = jnp.asarray(is_crowd).reshape(N, G)
    im_info = jnp.asarray(im_info, rois.dtype)
    roi_count = (jnp.full((N,), R, jnp.int32) if rois_num is None
                 else jnp.asarray(rois_num, jnp.int32))
    gt_count = (jnp.full((N,), G, jnp.int32) if gt_num is None
                else jnp.asarray(gt_num, jnp.int32))
    max_ov_in = (jnp.zeros((N, R), rois.dtype) if max_overlap is None
                 else jnp.asarray(max_overlap).reshape(N, R))
    if is_cascade_rcnn and max_overlap is None:
        raise InvalidArgumentError(
            "max_overlap is required when is_cascade_rcnn=True "
            "(generate_proposal_labels_op.cc InferShape)")
    key = _require_key(key, use_random, "generate_proposal_labels")
    cfg = (int(batch_size_per_im), float(fg_fraction), float(fg_thresh),
           float(bg_thresh_hi), float(bg_thresh_lo),
           tuple(float(w) for w in bbox_reg_weights), int(class_nums),
           bool(use_random), bool(is_cascade_rcnn), bool(is_cls_agnostic))

    keys = jax.random.split(key, N)
    outs = jax.vmap(
        lambda r, rc, gc, cr, g, gn, ii, mo, k: _proposal_labels_one(
            r, rc, gc, cr, g, gn, ii, mo, cfg, k)
    )(rois, roi_count, gt_classes, is_crowd, gt_boxes, gt_count, im_info,
      max_ov_in, keys)
    (rois_out, labels, tgt, in_w, out_w, max_ov, counts) = outs
    cap = rois_out.shape[1]
    res = (rois_out.reshape(N * cap, 4), labels.reshape(N * cap, 1),
           tgt.reshape(N * cap, -1), in_w.reshape(N * cap, -1),
           out_w.reshape(N * cap, -1))
    if return_max_overlap:
        return res + (max_ov.reshape(N * cap),)
    return res


def rasterize_polygon(verts, nv, resolution, box):
    """Fill one polygon onto a ``resolution²`` grid relative to ``box``.

    verts ``[V, 2]`` (x, y) with ``nv`` valid vertices; pixel centers
    inside the polygon (even-odd crossing rule) are 1.  This replaces the
    reference's COCO 5x-upsampled boundary-trace fill (mask_util.cc:45)
    with a vectorized point-in-polygon test — identical on axis-aligned
    shapes, ±1 boundary pixel on slanted edges.
    """
    M = int(resolution)
    V = verts.shape[0]
    x0, y0 = box[0], box[1]
    w = jnp.maximum(box[2] - box[0], 1.0)
    h = jnp.maximum(box[3] - box[1], 1.0)
    px = (verts[:, 0] - x0) * M / w
    py = (verts[:, 1] - y0) * M / h
    # pixel centers
    cx = jnp.arange(M) + 0.5
    cy = jnp.arange(M) + 0.5
    gx, gy = jnp.meshgrid(cx, cy)           # [M, M] (row = y)
    idx = jnp.arange(V)
    nxt = jnp.where(idx + 1 >= nv, 0, idx + 1)
    valid_edge = idx < nv
    x1, y1 = px[idx], py[idx]
    x2, y2 = px[nxt], py[nxt]
    # crossing-number: edge crosses the horizontal ray at gy
    gyb = gy[None, :, :]
    gxb = gx[None, :, :]
    y1b, y2b = y1[:, None, None], y2[:, None, None]
    x1b, x2b = x1[:, None, None], x2[:, None, None]
    cond = ((y1b > gyb) != (y2b > gyb)) & valid_edge[:, None, None]
    t = (gyb - y1b) / jnp.where(y2b == y1b, 1.0, y2b - y1b)
    xi = x1b + t * (x2b - x1b)
    cross = cond & (gxb < xi)
    return (jnp.sum(cross, axis=0) % 2).astype(jnp.int32)  # [M, M]


def _mask_labels_one(im_info, gt_cls, crowd, polys, poly_nv, poly_count,
                     gt_count, rois, labels, roi_count, C, M):
    """SampleMaskForOneImage (generate_mask_labels_op.cc:138-300), dense."""
    G, Pp = polys.shape[0], polys.shape[1]
    R = rois.shape[0]
    im_scale = im_info[2]

    valid_gt = (jnp.arange(G) < gt_count) & (gt_cls > 0) & (crowd == 0)
    # Poly2Boxes: bbox of all polys of each gt
    vx = polys[..., 0]
    vy = polys[..., 1]
    vmask = (jnp.arange(polys.shape[2])[None, None, :] < poly_nv[..., None]) \
        & (jnp.arange(Pp)[None, :, None] < poly_count[:, None, None])
    big = jnp.asarray(jnp.inf, vx.dtype)
    bx0 = jnp.min(jnp.where(vmask, vx, big), axis=(1, 2))
    by0 = jnp.min(jnp.where(vmask, vy, big), axis=(1, 2))
    bx1 = jnp.max(jnp.where(vmask, vx, -big), axis=(1, 2))
    by1 = jnp.max(jnp.where(vmask, vy, -big), axis=(1, 2))
    poly_boxes = jnp.stack([bx0, by0, bx1, by1], axis=-1)  # [G, 4]
    poly_boxes = jnp.where(valid_gt[:, None], poly_boxes, 0.0)

    valid_roi = jnp.arange(R) < roi_count
    fg = valid_roi & (labels > 0)
    fg_num = fg.sum()
    src, valid = _compact_indices(fg, R)
    safe = jnp.clip(src, 0, R - 1)
    rois_fg = rois[safe] / im_scale

    ov = iou_similarity(rois_fg, poly_boxes, box_normalized=False)
    ov = jnp.where(valid_gt[None, :], ov, -big)
    g_for_roi = jnp.argmax(ov, axis=1)                    # [R]

    def mask_for(gi, roi):
        def poly_mask(p):
            return rasterize_polygon(polys[gi, p], poly_nv[gi, p], M, roi)
        masks = jax.vmap(poly_mask)(jnp.arange(Pp))
        present = (jnp.arange(Pp) < poly_count[gi])[:, None, None]
        return (jnp.sum(jnp.where(present, masks, 0), axis=0) > 0)

    masks = jax.vmap(mask_for)(g_for_roi, rois_fg)        # [R, M, M] bool
    cls = jnp.where(valid, labels[safe], 0)

    # no-fg fallback (op.cc:260-284): one all-ignore mask on roi 0, class 0
    no_fg = fg_num == 0
    count = jnp.maximum(fg_num, 1)
    roi0 = rois[0] / im_scale
    rois_fg = jnp.where(no_fg, jnp.broadcast_to(roi0, rois_fg.shape), rois_fg)
    first_bg = jnp.argmax(valid_roi & (labels == 0))
    has_mask_idx = jnp.where(valid, safe, 0)
    has_mask_idx = jnp.where(no_fg,
                             jnp.full_like(has_mask_idx, first_bg),
                             has_mask_idx).astype(jnp.int32)

    # ExpandMaskTarget: [R, C*M*M], -1 everywhere except the class slot
    flat = masks.reshape(R, M * M).astype(jnp.int32)
    flat = jnp.where(no_fg, -1, flat)  # fallback mask is all ignore
    onehot = jax.nn.one_hot(cls, C, dtype=jnp.int32)      # [R, C]
    expand = jnp.where((onehot[:, :, None] > 0) & (cls[:, None, None] > 0),
                       flat[:, None, :], -1).reshape(R, C * M * M)
    row_valid = jnp.arange(R) < count
    expand = jnp.where(row_valid[:, None], expand, -1)
    rois_out = jnp.where(row_valid[:, None], rois_fg * im_scale, 0.0)
    return rois_out, has_mask_idx, expand, count.astype(jnp.int32)


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         gt_num=None, rois_num=None, poly_vertex_num=None,
                         poly_num=None):
    """Mask-RCNN mask head targets (ref: detection.py:2742 over
    generate_mask_labels_op.cc).

    Dense contract: ``gt_segms [N, G, Pp, V, 2]`` polygon vertex arrays
    (zero-padded), ``poly_vertex_num [N, G, Pp]`` valid vertices per
    polygon, ``poly_num [N, G]`` polygons per gt, ``rois [N, R, 4]`` +
    ``labels_int32 [N, R]`` from :func:`generate_proposal_labels` (label
    -1 padding allowed), per-image counts as elsewhere.

    Returns (mask_rois ``[N*R, 4]``, roi_has_mask_int32 ``[N*R, 1]``
    (index into the per-image roi list), mask_int32
    ``[N*R, num_classes*resolution²]`` with -1 = ignore, mask_num ``[N]``
    valid rows per image).
    """
    rois = jnp.asarray(rois)
    segms = jnp.asarray(gt_segms)
    if rois.ndim != 3 or segms.ndim != 5:
        raise InvalidArgumentError(
            "generate_mask_labels dense contract wants rois [N,R,4] and "
            f"gt_segms [N,G,Pp,V,2]; got {rois.shape}, {segms.shape}")
    N, R = rois.shape[0], rois.shape[1]
    G, Pp, V = segms.shape[1], segms.shape[2], segms.shape[3]
    labels = jnp.asarray(labels_int32).reshape(N, R)
    gt_classes = jnp.asarray(gt_classes).reshape(N, G)
    is_crowd = jnp.asarray(is_crowd).reshape(N, G)
    im_info = jnp.asarray(im_info, rois.dtype)
    nv = (jnp.full((N, G, Pp), V, jnp.int32) if poly_vertex_num is None
          else jnp.asarray(poly_vertex_num, jnp.int32))
    pc = (jnp.full((N, G), Pp, jnp.int32) if poly_num is None
          else jnp.asarray(poly_num, jnp.int32))
    gt_count = (jnp.full((N,), G, jnp.int32) if gt_num is None
                else jnp.asarray(gt_num, jnp.int32))
    roi_count = (jnp.full((N,), R, jnp.int32) if rois_num is None
                 else jnp.asarray(rois_num, jnp.int32))

    outs = jax.vmap(
        lambda ii, gc, cr, pl, pnv, pcnt, gn, r, lb, rc: _mask_labels_one(
            ii, gc, cr, pl, pnv, pcnt, gn, r, lb, rc,
            int(num_classes), int(resolution))
    )(im_info, gt_classes, is_crowd, segms, nv, pc, gt_count, rois, labels,
      roi_count)
    rois_out, has_mask, expand, counts = outs
    return (rois_out.reshape(N * R, 4),
            has_mask.reshape(N * R, 1),
            expand.reshape(N * R, -1),
            counts)

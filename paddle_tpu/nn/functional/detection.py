"""Detection ops — IoU, matching, target assignment, SSD multibox loss.

Parity surface: fluid/layers/detection.py (iou_similarity:763,
box_coder:816, bipartite_match, target_assign, ssd_loss:1510,
prior_box:1761) over the C++ kernels in operators/detection/
(iou_similarity_op.h, bipartite_match_op.cc:67-186,
mine_hard_examples_op.cc:52-155, box_coder_op.h, prior_box_op.h).

TPU-native redesign: the reference threads ragged per-image ground truth
through LoD tensors and sequential CPU kernels.  Here everything is
dense and batch-first — ground truth arrives padded ``[N, G, 4]`` where
padding rows are all-zero boxes.  A zero-area box has IoU 0 with
everything and the matcher ignores distances below eps (the same guard
the reference kernel uses, bipartite_match_op.cc:124), so padding is
inert without masks.  The greedy bipartite match is a ``lax.fori_loop``
(G rounds of a masked global argmax), hard-negative mining is a dense
rank-vs-quota select — no host round-trips, the whole SSD loss jits.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.errors import InvalidArgumentError

__all__ = [
    "iou_similarity", "box_coder", "bipartite_match", "target_assign",
    "mine_hard_examples", "ssd_loss", "prior_box", "nms",
    "multiclass_nms", "detection_output", "box_clip", "roi_align",
    "roi_pool", "sigmoid_focal_loss", "yolo_box", "yolov3_loss",
    "matrix_nms", "density_prior_box", "anchor_generator",
    "generate_proposals", "box_decoder_and_assign",
    "distribute_fpn_proposals", "collect_fpn_proposals", "psroi_pool",
    "locality_aware_nms",
]

import math as _math

#: exp() clamp in proposal decoding (bbox_util.h kBBoxClipDefault)
_BBOX_CLIP = _math.log(1000.0 / 16.0)

_EPS = 1e-6


def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU between box sets (ref kernel
    operators/detection/iou_similarity_op.h — +1 edge length when boxes
    are in pixel coordinates, i.e. ``box_normalized=False``).

    x ``[..., M, 4]``, y ``[..., P, 4]`` (xmin, ymin, xmax, ymax) →
    ``[..., M, P]``.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y, x.dtype)
    off = 0.0 if box_normalized else 1.0
    ax = x[..., :, None, :]  # [M, 1, 4]
    ay = y[..., None, :, :]  # [1, P, 4]
    inter_min = jnp.maximum(ax[..., :2], ay[..., :2])
    inter_max = jnp.minimum(ax[..., 2:], ay[..., 2:])
    inter_wh = jnp.maximum(inter_max - inter_min + off, 0.0)
    inter = inter_wh[..., 0] * inter_wh[..., 1]
    area = lambda b: ((b[..., 2] - b[..., 0] + off)
                      * (b[..., 3] - b[..., 1] + off))
    union = area(ax) + area(ay) - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, _EPS), 0.0)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    """Encode/decode boxes against priors (ref: operators/detection/
    box_coder_op.h).  encode: target ``[M, 4]`` × prior ``[P, 4]`` →
    ``[M, P, 4]`` center-size offsets scaled by ``prior_box_var``;
    decode: target ``[M, P, 4]`` (or broadcast priors along ``axis``) →
    corner boxes."""
    pb = jnp.asarray(prior_box)
    tb = jnp.asarray(target_box)
    off = 0.0 if box_normalized else 1.0
    pbw = pb[..., 2] - pb[..., 0] + off
    pbh = pb[..., 3] - pb[..., 1] + off
    pbx = pb[..., 0] + pbw * 0.5
    pby = pb[..., 1] + pbh * 0.5
    if prior_box_var is None:
        var = jnp.ones((4,), pb.dtype)
    else:
        var = jnp.asarray(prior_box_var, pb.dtype)
    if var.ndim not in (1, 2) or var.shape[-1] != 4:
        raise InvalidArgumentError(
            f"prior_box_var must be a 4-vector or [P, 4], got {var.shape}")
    if axis not in (0, 1):
        raise InvalidArgumentError(f"axis must be 0 or 1, got {axis}")

    if code_type == "encode_center_size":
        # encode ignores axis (box_coder_op.h EncodeCenterSize): target [M,4]
        # x prior [P,4] -> [M,P,4]; a [P,4] prior_box_var divides per column
        tbw = tb[..., 2] - tb[..., 0] + off
        tbh = tb[..., 3] - tb[..., 1] + off
        tbx = tb[..., 0] + tbw * 0.5
        tby = tb[..., 1] + tbh * 0.5
        # pairwise: [..., M, 1] vs [P]
        ex = (tbx[..., :, None] - pbx) / pbw
        ey = (tby[..., :, None] - pby) / pbh
        ew = jnp.log(jnp.maximum(tbw[..., :, None] / pbw, _EPS))
        eh = jnp.log(jnp.maximum(tbh[..., :, None] / pbh, _EPS))
        out = jnp.stack([ex, ey, ew, eh], axis=-1)
        if var.ndim == 2:  # [P, 4] broadcasts over [..., M, P, 4]
            return out / var
        return out / var.reshape((1,) * (out.ndim - 1) + (4,))
    if code_type == "decode_center_size":
        # decode (box_coder_op.h DecodeCenterSize): target [R, C, 4]; the
        # prior index is the COLUMN when axis=0 and the ROW when axis=1.
        if axis == 1 and tb.ndim >= 3:
            expand = lambda a: a[..., :, None]  # [P] -> [P, 1] (rows)
        else:
            expand = lambda a: a
        if var.ndim == 2 and axis == 1 and tb.ndim >= 3:
            v = var[:, None, :]  # [R, 4] -> [R, 1, 4] (per-row priors)
        else:
            v = var  # 4-vector, or [C, 4] broadcasting over [R, C, 4]
        t = tb * v
        cx = t[..., 0] * expand(pbw) + expand(pbx)
        cy = t[..., 1] * expand(pbh) + expand(pby)
        w = jnp.exp(t[..., 2]) * expand(pbw)
        h = jnp.exp(t[..., 3]) * expand(pbh)
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - off, cy + h * 0.5 - off], axis=-1)
    raise InvalidArgumentError(
        f"code_type must be encode/decode_center_size, got {code_type!r}")


def _bipartite_match_single(dist, match_type, threshold):
    """dist [G, P] → (col_match [P] int32, col_dist [P]).  Greedy global
    argmax, G rounds (ref kernel bipartite_match_op.cc:111-150), then the
    per_prediction argmax backfill (:153-186)."""
    G, P = dist.shape
    neg_inf = jnp.asarray(-jnp.inf, dist.dtype)

    def round_(state, _):
        col_match, col_dist, row_used = state
        masked = jnp.where(row_used[:, None] | (col_match != -1)[None, :],
                           neg_inf, dist)
        flat = jnp.argmax(masked)
        i, j = flat // P, flat % P
        best = masked[i, j]
        ok = best >= _EPS  # kernel skips dist < eps pairs
        col_match = jnp.where(ok, col_match.at[j].set(i.astype(jnp.int32)),
                              col_match)
        col_dist = jnp.where(ok, col_dist.at[j].set(best), col_dist)
        row_used = jnp.where(ok, row_used.at[i].set(True), row_used)
        return (col_match, col_dist, row_used), None

    init = (jnp.full((P,), -1, jnp.int32), jnp.zeros((P,), dist.dtype),
            jnp.zeros((G,), bool))
    (col_match, col_dist, _), _ = jax.lax.scan(round_, init, None, length=G)

    if match_type == "per_prediction":
        # the op attr defaults to 0.5 when unset (bipartite_match_op.cc
        # SetDefault(0.5)); eps here would backfill any positive-IoU prior
        thr = 0.5 if threshold is None else max(float(threshold), _EPS)
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_dist = jnp.max(dist, axis=0)
        backfill = (col_match == -1) & (best_dist >= thr)
        col_match = jnp.where(backfill, best_row, col_match)
        col_dist = jnp.where(backfill, best_dist, col_dist)
    return col_match, col_dist


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=None, name=None):
    """Greedy bipartite (+ optional per-prediction argmax) matching
    (ref: fluid/layers/detection.py bipartite_match over
    bipartite_match_op.cc).  dist ``[G, P]`` or batched ``[N, G, P]`` →
    (match_indices ``[N, P]`` int32 gt-row or -1, match_dist ``[N, P]``).
    """
    dist = jnp.asarray(dist_matrix)
    squeeze = dist.ndim == 2
    if squeeze:
        dist = dist[None]
    fn = lambda d: _bipartite_match_single(d, match_type, dist_threshold)
    idx, d = jax.vmap(fn)(dist)
    return idx, d


def target_assign(x, match_indices, negative_mask=None, mismatch_value=0,
                  name=None):
    """Gather per-prior targets by match index (ref: target_assign_op +
    detection.py target_assign; the reference feeds ragged negatives as
    a LoD index list — dense form: a ``[N, P]`` bool mask).

    x ``[N, G, K]`` (shared per-gt targets, e.g. labels) or
    ``[N, G, P, K]`` (per-(gt, prior) targets, e.g. encoded boxes);
    match_indices ``[N, P]`` → (out ``[N, P, K]``, weight ``[N, P, 1]``).
    """
    x = jnp.asarray(x)
    mi = jnp.asarray(match_indices)
    matched = mi != -1
    safe = jnp.maximum(mi, 0)
    N, P = mi.shape
    if x.ndim == 3:  # [N, G, K]
        out = jnp.take_along_axis(x, safe[:, :, None], axis=1)
    elif x.ndim == 4:  # [N, G, P, K]: out[n, p] = x[n, match[n, p], p]
        out = x[jnp.arange(N)[:, None], safe, jnp.arange(P)[None, :], :]
    else:
        raise InvalidArgumentError(
            f"target_assign expects rank-3/4 x, got shape {x.shape}")
    out = jnp.where(matched[:, :, None], out,
                    jnp.asarray(mismatch_value, out.dtype))
    weight = matched.astype(x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                            else jnp.float32)
    if negative_mask is not None:
        weight = jnp.maximum(weight,
                             jnp.asarray(negative_mask, weight.dtype))
    return out, weight[:, :, None]


def mine_hard_examples(cls_loss, match_indices, match_dist,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       mining_type="max_negative", sample_size=None,
                       loc_loss=None):
    """Hard-negative mining (ref kernel mine_hard_examples_op.cc:52-155).

    max_negative: candidates are unmatched priors with overlap below
    ``neg_dist_threshold``; the ``min(num_pos·ratio, #candidates)``
    highest-classification-loss candidates become negatives.  Returns a
    dense ``(neg_mask [N, P] bool, updated_match_indices)`` — the mask is
    the LoD NegIndices list in dense form.
    """
    if mining_type != "max_negative":
        raise InvalidArgumentError(
            "Only mining_type='max_negative' is supported (the reference "
            "op registers hard_example but ssd_loss rejects it too, "
            "detection.py:1644)")
    loss = jnp.asarray(cls_loss)
    mi = jnp.asarray(match_indices)
    dist = jnp.asarray(match_dist)
    eligible = (mi == -1) & (dist < neg_dist_threshold)
    num_pos = jnp.sum(mi != -1, axis=1)
    quota = jnp.minimum((num_pos * neg_pos_ratio).astype(jnp.int32),
                        jnp.sum(eligible, axis=1).astype(jnp.int32))
    masked = jnp.where(eligible, loss, -jnp.inf)
    order = jnp.argsort(-masked, axis=1)
    P = loss.shape[1]
    ranks = jnp.zeros_like(order).at[
        jnp.arange(loss.shape[0])[:, None], order].set(
        jnp.broadcast_to(jnp.arange(P), loss.shape))
    neg_mask = eligible & (ranks < quota[:, None])
    return neg_mask, mi


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """SSD multibox loss (ref: fluid/layers/detection.py:1510 — the same
    5 stages: match, mining-pass confidence loss, hard-negative mining,
    target assignment, weighted SmoothL1 + softmax-CE).

    Dense batch-first signature: location ``[N, P, 4]``, confidence
    ``[N, P, C]``, gt_box ``[N, G, 4]`` (zero-padded rows inert),
    gt_label ``[N, G]`` or ``[N, G, 1]``, prior_box ``[P, 4]`` →
    per-image loss ``[N, 1]``.
    """
    from .loss import softmax_with_cross_entropy

    location = jnp.asarray(location)
    confidence = jnp.asarray(confidence)
    gt_box = jnp.asarray(gt_box)
    gt_label = jnp.asarray(gt_label)
    if gt_label.ndim == 3:
        gt_label = gt_label[..., 0]
    N, P, C = confidence.shape

    # 1. match ground truth to priors
    iou = iou_similarity(gt_box, jnp.asarray(prior_box))  # [N, G, P]
    matched_indices, matched_dist = bipartite_match(iou, match_type,
                                                    overlap_threshold)

    # 2. confidence loss for mining
    target_label, _ = target_assign(
        gt_label[:, :, None].astype(jnp.int64), matched_indices,
        mismatch_value=background_label)
    conf_loss = softmax_with_cross_entropy(
        confidence.reshape(N * P, C),
        target_label.reshape(N * P, 1).astype(jnp.int64))
    conf_loss = jax.lax.stop_gradient(conf_loss.reshape(N, P))

    # 3. hard-negative mining
    neg_mask, updated_indices = mine_hard_examples(
        conf_loss, matched_indices, matched_dist,
        neg_pos_ratio=neg_pos_ratio, neg_dist_threshold=neg_overlap,
        mining_type=mining_type, sample_size=sample_size)

    # 4. regression + classification targets
    encoded_bbox = box_coder(prior_box, prior_box_var, gt_box,
                             code_type="encode_center_size")  # [N, G, P, 4]
    target_bbox, target_loc_weight = target_assign(
        encoded_bbox, updated_indices, mismatch_value=background_label)
    target_label, target_conf_weight = target_assign(
        gt_label[:, :, None].astype(jnp.int64), updated_indices,
        negative_mask=neg_mask, mismatch_value=background_label)

    # 5. weighted losses
    conf_loss = softmax_with_cross_entropy(
        confidence.reshape(N * P, C),
        jax.lax.stop_gradient(target_label).reshape(N * P, 1))
    conf_loss = conf_loss.reshape(N, P) * target_conf_weight[..., 0]

    diff = location - jax.lax.stop_gradient(target_bbox)
    ad = jnp.abs(diff)
    loc_loss = jnp.sum(jnp.where(ad < 1.0, 0.5 * ad * ad, ad - 0.5), -1)
    loc_loss = loc_loss * jax.lax.stop_gradient(target_loc_weight)[..., 0]

    loss = conf_loss_weight * conf_loss + loc_loss_weight * loc_loss
    loss = jnp.sum(loss, axis=1, keepdims=True)
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(target_loc_weight), _EPS)
    return loss


def nms(boxes, scores, score_threshold=-jnp.inf, nms_top_k=-1,
        nms_threshold=0.3, nms_eta=1.0, normalized=True):
    """Single-class greedy NMS → keep mask ``[M]`` bool (transcribes
    NMSFast, multiclass_nms_op.cc:139-192, incl. the adaptive-eta
    threshold decay after each kept box).

    TPU-native: candidates are score-sorted once (lax.top_k), the
    pairwise IoU matrix is computed up front, and the inherently
    sequential keep decision is a ``lax.fori_loop`` over the (bounded)
    candidate list — one compiled loop, no host round-trips.
    """
    boxes = jnp.asarray(boxes)
    scores = jnp.asarray(scores)
    M = boxes.shape[0]
    s = jnp.where(scores > score_threshold, scores, -jnp.inf)
    k = M if nms_top_k is None or nms_top_k < 0 else min(int(nms_top_k), M)
    top_s, order = jax.lax.top_k(s, k)
    iou = iou_similarity(boxes[order], boxes[order], normalized)
    idx = jnp.arange(k)

    def body(i, state):
        keep, thr = state
        suppressed = jnp.any(keep & (idx < i) & (iou[i] > thr))
        ok = (~suppressed) & jnp.isfinite(top_s[i])
        keep = keep.at[i].set(ok)
        thr = jnp.where(ok & (nms_eta < 1.0) & (thr > 0.5),
                        thr * nms_eta, thr)  # :188-190
        return keep, thr

    keep_sorted, _ = jax.lax.fori_loop(
        0, k, body,
        (jnp.zeros((k,), bool), jnp.asarray(nms_threshold, jnp.float32)))
    return jnp.zeros((M,), bool).at[order].set(keep_sorted)


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None, return_num=False):
    """Multi-class NMS (ref: fluid/layers/detection.py:3256 over
    multiclass_nms_op.cc).  bboxes ``[N, M, 4]``, scores ``[N, C, M]``.

    Dense output (the reference emits a ragged LoD tensor): ``[N, K, 6]``
    rows of (label, score, xmin, ymin, xmax, ymax) sorted by score,
    padded with label=-1 (the reference's empty-result marker), where
    ``K = keep_top_k`` (or C·M when keep_top_k=-1).  With
    ``return_num=True`` also returns kept counts ``[N]``.
    """
    bboxes = jnp.asarray(bboxes)
    scores = jnp.asarray(scores)
    N, M = bboxes.shape[0], bboxes.shape[1]
    C = scores.shape[1]
    # drop the background class BEFORE the per-class NMS vmap — its
    # result would be discarded, and the sequential NMS loop is the
    # expensive part of this op
    if 0 <= background_label < C:
        fg_rows = [c for c in range(C) if c != background_label]
        fg_labels = jnp.asarray(fg_rows, jnp.int32)
        scores = scores[:, fg_labels, :]
        Cf = C - 1
    else:
        fg_labels = jnp.arange(C, dtype=jnp.int32)
        Cf = C
    K = Cf * M if keep_top_k is None or keep_top_k < 0 else min(
        int(keep_top_k), Cf * M)

    def image(boxes, sc):  # boxes [M,4], sc [Cf,M]
        keep = jax.vmap(lambda s1: nms(
            boxes, s1, score_threshold, nms_top_k, nms_threshold,
            nms_eta, normalized))(sc)  # [Cf, M]
        flat = jnp.where(keep.reshape(-1), sc.reshape(-1), -jnp.inf)
        top_s, top_i = jax.lax.top_k(flat, K)  # keep-top-k across classes
        label = fg_labels[top_i // M].astype(bboxes.dtype)
        box = boxes[top_i % M]
        valid = jnp.isfinite(top_s)
        row = jnp.concatenate(
            [label[:, None], top_s[:, None], box], axis=-1)
        row = jnp.where(valid[:, None], row, -1.0)
        return row, valid.sum().astype(jnp.int32)

    out, nums = jax.vmap(image)(bboxes, scores)
    return (out, nums) if return_num else out


def anchor_generator(input, anchor_sizes, aspect_ratios,
                     variances=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    """Faster-RCNN anchor grid (ref: operators/detection/
    anchor_generator_op.h:30-90): per cell, one pixel-coordinate anchor
    per (aspect_ratio, anchor_size) with the kernel's rounded base
    extents.  → (anchors ``[H, W, K, 4]``, variances same shape)."""
    H, W = input.shape[2], input.shape[3]
    sw, sh = float(stride[0]), float(stride[1])

    def _round_half_up(v):  # C++ round(): half away from zero — Python's
        return _math.floor(v + 0.5)  # banker's rounding diverges at .5

    whs = []
    for ar in aspect_ratios:
        base_w = _round_half_up(_math.sqrt(sw * sh / ar))
        base_h = _round_half_up(base_w * ar)
        for size in anchor_sizes:
            whs.append((size / sw * base_w, size / sh * base_h))
    wh = jnp.asarray(whs, jnp.float32)  # [K, 2]
    cx = jnp.arange(W, dtype=jnp.float32) * sw + offset * (sw - 1)
    cy = jnp.arange(H, dtype=jnp.float32) * sh + offset * (sh - 1)
    cxg = jnp.broadcast_to(cx[None, :, None], (H, W, wh.shape[0]))
    cyg = jnp.broadcast_to(cy[:, None, None], (H, W, wh.shape[0]))
    anchors = jnp.stack([
        cxg - 0.5 * (wh[:, 0] - 1), cyg - 0.5 * (wh[:, 1] - 1),
        cxg + 0.5 * (wh[:, 0] - 1), cyg + 0.5 * (wh[:, 1] - 1),
    ], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           anchors.shape)
    return anchors, var


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None,
                       return_rois_num=False):
    """RPN proposal generation (ref: fluid/layers/detection.py
    generate_proposals over generate_proposals_op.cc:165-260): per
    image, take the pre_nms_top_n highest-scoring anchors, decode their
    deltas (center-format, +1-pixel widths, exp clamped at
    log(1000/16), variance-scaled — bbox_util.h BoxCoder), clip to the
    image, drop boxes smaller than min_size at original scale
    (FilterBoxes with is_scale=true), greedy-NMS, keep post_nms_top_n.

    scores ``[N, A, H, W]``, bbox_deltas ``[N, 4A, H, W]``, im_info
    ``[N, 3]`` (h, w, scale), anchors/variances ``[H, W, A, 4]`` →
    dense (rois ``[N, K, 4]``, roi_probs ``[N, K, 1]``) padded with
    zero boxes / -1 scores; ``return_rois_num`` adds kept counts.
    """
    scores = jnp.asarray(scores)
    deltas = jnp.asarray(bbox_deltas, scores.dtype)
    im_info = jnp.asarray(im_info, scores.dtype)
    anchors = jnp.asarray(anchors, scores.dtype).reshape(-1, 4)
    variances = jnp.asarray(variances, scores.dtype).reshape(-1, 4)
    N, A, H, W = scores.shape
    M = A * H * W
    # kernel transposes NCHW→NHWC then flattens rows of 4: order (h,w,a)
    s_flat = jnp.transpose(scores, (0, 2, 3, 1)).reshape(N, M)
    d_flat = jnp.transpose(deltas, (0, 2, 3, 1)).reshape(N, M, 4)
    k = M if pre_nms_top_n <= 0 else min(int(pre_nms_top_n), M)
    K = min(int(post_nms_top_n), k) if post_nms_top_n > 0 else k

    def one(s, d, info):
        top_s, idx = jax.lax.top_k(s, k)
        anc = anchors[idx]
        var = variances[idx]
        dd = d[idx]
        aw = anc[:, 2] - anc[:, 0] + 1.0
        ah = anc[:, 3] - anc[:, 1] + 1.0
        acx = anc[:, 0] + 0.5 * aw
        acy = anc[:, 1] + 0.5 * ah
        cx = var[:, 0] * dd[:, 0] * aw + acx
        cy = var[:, 1] * dd[:, 1] * ah + acy
        bw = jnp.exp(jnp.minimum(var[:, 2] * dd[:, 2], _BBOX_CLIP)) * aw
        bh = jnp.exp(jnp.minimum(var[:, 3] * dd[:, 3], _BBOX_CLIP)) * ah
        props = jnp.stack([cx - 0.5 * bw, cy - 0.5 * bh,
                           cx + 0.5 * bw - 1, cy + 0.5 * bh - 1], axis=-1)
        # clip to image window (ClipTiledBoxes)
        imh, imw, imscale = info[0], info[1], info[2]
        props = jnp.stack([
            jnp.clip(props[:, 0], 0, imw - 1),
            jnp.clip(props[:, 1], 0, imh - 1),
            jnp.clip(props[:, 2], 0, imw - 1),
            jnp.clip(props[:, 3], 0, imh - 1)], axis=-1)
        # FilterBoxes, is_scale=true: min side at ORIGINAL image scale
        ms = jnp.maximum(min_size, 1.0)
        ws = (props[:, 2] - props[:, 0]) / imscale + 1.0
        hs = (props[:, 3] - props[:, 1]) / imscale + 1.0
        ctr_x = props[:, 0] + (props[:, 2] - props[:, 0] + 1) / 2
        ctr_y = props[:, 1] + (props[:, 3] - props[:, 1] + 1) / 2
        ok = ((ws >= ms) & (hs >= ms)
              & (ctr_x <= imw) & (ctr_y <= imh))
        s_kept = jnp.where(ok, top_s, -jnp.inf)
        keep = nms(props, s_kept, score_threshold=-jnp.inf,
                   nms_top_k=-1, nms_threshold=nms_thresh, nms_eta=eta,
                   normalized=False)
        final_s = jnp.where(keep & jnp.isfinite(s_kept), s_kept, -jnp.inf)
        out_s, out_i = jax.lax.top_k(final_s, K)
        valid = jnp.isfinite(out_s)
        rois = jnp.where(valid[:, None], props[out_i], 0.0)
        return rois, jnp.where(valid, out_s, -1.0)[:, None], \
            valid.sum().astype(jnp.int32)

    rois, probs, nums = jax.vmap(one)(s_flat, d_flat, im_info)
    if return_rois_num:
        return rois, probs, nums
    return rois, probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """Route ROIs to FPN levels by scale (ref: operators/detection/
    distribute_fpn_proposals_op.h:105-155): level =
    clip(⌊log2(√area/refer_scale + 1e-6)⌋ + refer_level, min, max) with
    +1-pixel areas.

    Dense contract: fpn_rois ``[R, 4]`` packed (valid rows first, padding
    a global suffix — ``rois_num`` scalar or per-image counts summing to
    the valid prefix), OR ``[N, K, 4]`` per-image padded blocks straight
    from :func:`generate_proposals` with ``rois_num [N]`` per-block valid
    counts.  Returns (list of ``[R, 4]`` zero-padded per-level tensors,
    restore_ind ``[R, 1]`` mapping each (flattened) input row to its
    position in the level-major compaction, list of per-level valid
    counts — the dense stand-in for the per-level LoD).
    """
    rois = jnp.asarray(fpn_rois)
    L = max_level - min_level + 1
    if rois.ndim == 3:
        # per-image padded blocks (generate_proposals layout): mask each
        # block's own padding tail before flattening
        NB, K = rois.shape[0], rois.shape[1]
        if rois_num is None:
            block_valid = jnp.ones((NB, K), bool)
        else:
            counts_in = jnp.asarray(rois_num, jnp.int32).reshape(NB)
            block_valid = jnp.arange(K)[None, :] < counts_in[:, None]
        valid_mask = block_valid.reshape(-1)
        rois = rois.reshape(-1, 4)
    else:
        valid_mask = None
    R = rois.shape[0]
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    area = jnp.where((w < 0) | (h < 0), 0.0, (w + 1) * (h + 1))
    lvl = jnp.floor(jnp.log2(jnp.sqrt(area) / refer_scale + 1e-6)
                    + refer_level)
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32) - min_level
    if valid_mask is not None:
        # zero-padding rows have +1-pixel area 1 and would route to
        # min_level as real ROIs; send them to an out-of-range level so
        # they drop from every level and count
        lvl = jnp.where(valid_mask, lvl, L)
    elif rois_num is not None:
        # packed contract: padding is a global suffix of sum(rois_num)
        valid = jnp.sum(jnp.asarray(rois_num, jnp.int32))
        lvl = jnp.where(jnp.arange(R) < valid, lvl, L)
    multi, counts = [], []
    rank_in_level = jnp.zeros((R,), jnp.int32)
    for i in range(L):
        m = lvl == i
        rank = jnp.cumsum(m.astype(jnp.int32)) - 1
        dest = jnp.where(m, rank, R)  # padding rows dropped
        out = jnp.zeros((R, 4), rois.dtype).at[dest].set(rois, mode="drop")
        multi.append(out)
        counts.append(m.sum().astype(jnp.int32))
        rank_in_level = jnp.where(m, rank, rank_in_level)
    offsets = jnp.cumsum(jnp.asarray([0] + [c for c in counts[:-1]]))
    # clip keeps padding rows (lvl == L sentinel) in bounds; their restore
    # entries are meaningless, as in the reference's LoD contract
    restore = (offsets[jnp.minimum(lvl, L - 1)]
               + rank_in_level).astype(jnp.int32)[:, None]
    return multi, restore, counts


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """Gather the top ``post_nms_top_n`` ROIs across FPN levels by score
    (ref: operators/detection/collect_fpn_proposals_op.h:109-148).
    Dense contract: per-level ``[Ri, 4]`` rois + ``[Ri]`` scores (with
    optional valid counts masking each level's padding) → (rois
    ``[K, 4]`` zero-padded, kept count)."""
    rois = jnp.concatenate([jnp.asarray(r) for r in multi_rois], axis=0)
    parts = [jnp.asarray(s).reshape(-1) for s in multi_scores]
    if rois_num_per_level is not None:
        parts = [jnp.where(jnp.arange(s.shape[0]) < n, s, -jnp.inf)
                 for s, n in zip(parts, rois_num_per_level)]
    scores = jnp.concatenate(parts)
    K = min(int(post_nms_top_n), scores.shape[0])
    top_s, idx = jax.lax.top_k(scores, K)
    valid = jnp.isfinite(top_s)
    out = jnp.where(valid[:, None], rois[idx], 0.0)
    return out, valid.sum().astype(jnp.int32)


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=None, name=None):
    """Cascade-RCNN head decode + per-ROI class assignment (ref:
    operators/detection/box_decoder_and_assign_op.h:30-100): decode the
    per-class deltas against each ROI (+1-pixel center-size, shared
    4-vector variance, exp clamp), then assign each ROI the decoded box
    of its best NON-background class — background-best ROIs keep the
    prior.

    prior_box ``[R, 4]``, prior_box_var ``[4]``, target_box
    ``[R, C·4]``, box_score ``[R, C]`` → (decode_box ``[R, C·4]``,
    assigned ``[R, 4]``)."""
    pb = jnp.asarray(prior_box)
    var = jnp.asarray(prior_box_var, pb.dtype).reshape(4)
    tb = jnp.asarray(target_box, pb.dtype)
    scores = jnp.asarray(box_score, pb.dtype)
    R = pb.shape[0]
    C = scores.shape[1]
    clip = _BBOX_CLIP if box_clip is None else float(box_clip)
    d = tb.reshape(R, C, 4)
    pw = pb[:, 2] - pb[:, 0] + 1.0
    ph = pb[:, 3] - pb[:, 1] + 1.0
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    cx = var[0] * d[..., 0] * pw[:, None] + pcx[:, None]
    cy = var[1] * d[..., 1] * ph[:, None] + pcy[:, None]
    bw = jnp.exp(jnp.minimum(var[2] * d[..., 2], clip)) * pw[:, None]
    bh = jnp.exp(jnp.minimum(var[3] * d[..., 3], clip)) * ph[:, None]
    decoded = jnp.stack([cx - bw / 2, cy - bh / 2,
                         cx + bw / 2 - 1, cy + bh / 2 - 1], axis=-1)
    # best class excluding background (class 0)
    fg_scores = scores.at[:, 0].set(-jnp.inf) if C > 1 else scores
    best = jnp.argmax(fg_scores, axis=1)
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    assigned = jnp.where((best > 0)[:, None], assigned, pb)
    return decoded.reshape(R, C * 4), assigned


def _sce(x, t):
    """Stable sigmoid cross-entropy (yolov3_loss_op.h SigmoidCrossEntropy)."""
    return jnp.maximum(x, 0.0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))


def _center_iou(x1, y1, w1, h1, x2, y2, w2, h2):
    """IoU of center-format boxes (yolov3_loss_op.h CalcBoxIoU)."""
    ow = jnp.minimum(x1 + w1 / 2, x2 + w2 / 2) - jnp.maximum(
        x1 - w1 / 2, x2 - w2 / 2)
    oh = jnp.minimum(y1 + h1 / 2, y2 + h2 / 2) - jnp.maximum(
        y1 - h1 / 2, y2 - h2 / 2)
    inter = jnp.where((ow < 0) | (oh < 0), 0.0, ow * oh)
    union = w1 * h1 + w2 * h2 - inter
    return inter / jnp.maximum(union, _EPS)


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss for one detection scale (ref:
    fluid/layers/detection.py:1019 over yolov3_loss_op.h:240-320).

    x ``[N, A·(5+cls), H, W]`` (A = len(anchor_mask)), gt_box
    ``[N, B, 4]`` center-format (cx, cy, w, h) normalized to the image
    (rows with w/h ≤ 0 are padding), gt_label ``[N, B]``, gt_score
    ``[N, B]`` mixup weights (None → 1) → per-image loss ``[N]``.

    Semantics kept from the kernel: predictions whose best IoU with any
    GT exceeds ``ignore_thresh`` drop out of the objectness loss; each
    GT matches the best whole-image anchor by wh-IoU and trains
    location (sigmoid-CE x/y + L1 w/h, scaled by ``(2-w·h)·score``),
    class (per-class sigmoid-CE with optional label smoothing) and
    objectness only when that anchor belongs to this scale's
    ``anchor_mask``.
    """
    x = jnp.asarray(x)
    gt_box = jnp.asarray(gt_box, x.dtype)
    gt_label = jnp.asarray(gt_label).astype(jnp.int32)
    N, _, H, W = x.shape
    A = len(anchor_mask)
    B = gt_box.shape[1]
    an_num = len(anchors) // 2
    anc = jnp.asarray(anchors, x.dtype).reshape(an_num, 2)
    mask = jnp.asarray(anchor_mask, jnp.int32)
    in_size = downsample_ratio * H
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)
    score = (jnp.asarray(gt_score, x.dtype) if gt_score is not None
             else jnp.ones((N, B), x.dtype))
    if use_label_smooth:
        delta = min(1.0 / class_num, 1.0 / 40)
        pos, neg = 1.0 - delta, delta
    else:
        pos, neg = 1.0, 0.0

    t = x.reshape(N, A, 5 + class_num, H, W)
    valid = (gt_box[..., 2] > 0) & (gt_box[..., 3] > 0)  # [N, B]

    # -- ignore mask: best pred-vs-gt IoU per cell ---------------------
    grid_x = jnp.arange(W, dtype=x.dtype)
    grid_y = jnp.arange(H, dtype=x.dtype).reshape(-1, 1)
    px = (grid_x + jax.nn.sigmoid(t[:, :, 0]) * scale + bias) / W
    py = (grid_y + jax.nn.sigmoid(t[:, :, 1]) * scale + bias) / H
    pw = jnp.exp(t[:, :, 2]) * anc[mask, 0].reshape(1, A, 1, 1) / in_size
    ph = jnp.exp(t[:, :, 3]) * anc[mask, 1].reshape(1, A, 1, 1) / in_size
    gx = gt_box[..., 0].reshape(N, 1, 1, 1, B)
    gy = gt_box[..., 1].reshape(N, 1, 1, 1, B)
    gw = gt_box[..., 2].reshape(N, 1, 1, 1, B)
    gh = gt_box[..., 3].reshape(N, 1, 1, 1, B)
    ious = _center_iou(px[..., None], py[..., None], pw[..., None],
                       ph[..., None], gx, gy, gw, gh)  # [N, A, H, W, B]
    ious = jnp.where(valid.reshape(N, 1, 1, 1, B), ious, 0.0)
    best_iou = jnp.max(ious, axis=-1)
    ignored = best_iou > ignore_thresh  # [N, A, H, W]

    # -- per-GT best anchor over the whole anchor set ------------------
    wh_iou = _center_iou(
        jnp.zeros(()), jnp.zeros(()),
        (anc[:, 0] / in_size).reshape(1, 1, an_num),
        (anc[:, 1] / in_size).reshape(1, 1, an_num),
        jnp.zeros(()), jnp.zeros(()),
        gt_box[..., 2:3], gt_box[..., 3:4])  # [N, B, an_num]
    best_n = jnp.argmax(wh_iou, axis=-1)  # [N, B]
    # anchor index → slot in this scale's mask, or -1
    mask_pos = jnp.full((an_num,), -1, jnp.int32).at[mask].set(
        jnp.arange(A, dtype=jnp.int32))
    mask_idx = mask_pos[best_n]  # [N, B]
    matched = valid & (mask_idx >= 0) & (gt_label >= 0)

    gi = (gt_box[..., 0] * W).astype(jnp.int32).clip(0, W - 1)
    gj = (gt_box[..., 1] * H).astype(jnp.int32).clip(0, H - 1)
    n_ix = jnp.broadcast_to(jnp.arange(N)[:, None], (N, B))
    a_ix = jnp.maximum(mask_idx, 0)
    pred_at = t[n_ix, a_ix, :, gj, gi]  # [N, B, 5+cls]

    tx = gt_box[..., 0] * W - gi
    ty = gt_box[..., 1] * H - gj
    anc_w = anc[best_n, 0]
    anc_h = anc[best_n, 1]
    tw = jnp.log(jnp.maximum(gt_box[..., 2] * in_size / anc_w, _EPS))
    th = jnp.log(jnp.maximum(gt_box[..., 3] * in_size / anc_h, _EPS))
    loc_scale = (2.0 - gt_box[..., 2] * gt_box[..., 3]) * score
    loc = (_sce(pred_at[..., 0], tx) + _sce(pred_at[..., 1], ty)
           + jnp.abs(pred_at[..., 2] - tw)
           + jnp.abs(pred_at[..., 3] - th)) * loc_scale
    cls_t = jnp.where(
        jnp.arange(class_num)[None, None, :] == gt_label[..., None],
        pos, neg)
    cls = jnp.sum(_sce(pred_at[..., 5:], cls_t), axis=-1) * score
    per_gt = jnp.where(matched, loc + cls, 0.0)
    loss = jnp.sum(per_gt, axis=1)  # [N]

    # -- objectness mask: 0 neg, -1 ignored, score at matched cells ----
    obj = jnp.where(ignored, -1.0, 0.0)
    flat = obj.reshape(N, A * H * W)
    cell = a_ix * (H * W) + gj * W + gi  # [N, B]
    cell = jnp.where(matched, cell, A * H * W)  # drop unmatched
    # kernel writes GTs in order t = 0..B-1, last write wins — scatter
    # .at[].set applies updates in index order per buffer, so write
    # sequentially to keep the tie semantics deterministic
    def write(i, f):  # unmatched rows carry an OOB cell → dropped
        return f.at[n_ix[:, i], cell[:, i]].set(score[:, i], mode="drop")

    flat = jax.lax.fori_loop(0, B, write, flat)
    obj = flat.reshape(N, A, H, W)
    pobj = t[:, :, 4]
    obj_loss = jnp.where(
        obj > 1e-5, _sce(pobj, 1.0) * obj,
        jnp.where(obj > -0.5, _sce(pobj, 0.0), 0.0))
    loss = loss + jnp.sum(obj_loss, axis=(1, 2, 3))
    return loss


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               name=None, return_rois_num=False):
    """Matrix NMS (ref: fluid/layers/detection.py:3540 over
    matrix_nms_op.cc NMSMatrix:100-166): instead of greedy suppression,
    every candidate's score decays by ``min_j f(iou_ij, iou_max_j)``
    over all higher-scored candidates j — gaussian
    ``exp((max²-iou²)·σ)`` or linear ``(1-iou)/(1-max)``.

    The whole computation is dense matrix algebra (no sequential loop),
    which is exactly why it exists — it vectorizes perfectly on TPU.
    bboxes ``[N, M, 4]``, scores ``[N, C, M]`` → dense ``[N, K, 6]``
    rows (label, decayed_score, box), label=-1 padding, K=keep_top_k.
    """
    bboxes = jnp.asarray(bboxes)
    scores = jnp.asarray(scores)
    N, M = bboxes.shape[0], bboxes.shape[1]
    C = scores.shape[1]
    if 0 <= background_label < C:
        fg = [c for c in range(C) if c != background_label]
        fg_labels = jnp.asarray(fg, jnp.int32)
        scores = scores[:, fg_labels, :]
        Cf = C - 1
    else:
        fg_labels = jnp.arange(C, dtype=jnp.int32)
        Cf = C
    k = M if nms_top_k is None or nms_top_k < 0 else min(int(nms_top_k), M)
    K = Cf * k if keep_top_k is None or keep_top_k < 0 else min(
        int(keep_top_k), Cf * k)
    idx = jnp.arange(k)
    strict_lower = idx[:, None] > idx[None, :]  # j < i

    def one_class(boxes, s):  # [M, 4], [M]
        s = jnp.where(s > score_threshold, s, -jnp.inf)
        top_s, order = jax.lax.top_k(s, k)
        iou = iou_similarity(boxes[order], boxes[order], normalized)
        iou_l = jnp.where(strict_lower, iou, 0.0)
        iou_max = jnp.max(iou_l, axis=1)  # max over j<i (0 for i=0)
        if use_gaussian:
            decay_m = jnp.exp((iou_max[None, :] ** 2 - iou_l ** 2)
                              * gaussian_sigma)
        else:  # eps keeps a duplicate box (max_iou→1) from NaN-poisoning
            decay_m = (1.0 - iou_l) / jnp.maximum(
                1.0 - iou_max[None, :], _EPS)
        decay = jnp.min(jnp.where(strict_lower, decay_m, 1.0), axis=1)
        ds = decay * top_s
        ds = jnp.where(jnp.isfinite(top_s) & (ds > post_threshold),
                       ds, -jnp.inf)
        return ds, order

    def image(boxes, sc):  # [M, 4], [Cf, M]
        ds, order = jax.vmap(lambda s1: one_class(boxes, s1))(sc)
        flat = ds.reshape(-1)
        top_s, top_i = jax.lax.top_k(flat, K)
        cls = top_i // k
        box_idx = order.reshape(-1)[top_i]
        box = boxes[box_idx]
        valid = jnp.isfinite(top_s)
        row = jnp.concatenate(
            [fg_labels[cls].astype(bboxes.dtype)[:, None],
             top_s[:, None], box], axis=-1)
        return (jnp.where(valid[:, None], row, -1.0),
                jnp.where(valid, box_idx.astype(jnp.int32), -1),
                valid.sum().astype(jnp.int32))

    out, index, nums = jax.vmap(image)(bboxes, scores)
    rets = (out,)
    if return_index:
        rets += (index,)  # [N, K] box index per kept row, -1 padding
    if return_rois_num:
        rets += (nums,)
    return rets[0] if len(rets) == 1 else rets


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """Locality-aware NMS for dense text detection / EAST (ref:
    fluid/layers/detection.py locality_aware_nms over
    locality_aware_nms_op.cc:96-135): a single ordered pass
    score-weighted-merges each box into the current merge head while
    they overlap above ``nms_threshold`` (head score accumulates), then
    standard greedy NMS prunes the merged candidates.  Single class
    (the reference op supports only C=1): bboxes ``[N, M, 4]``, scores
    ``[N, 1, M]`` → dense ``[N, K, 6]`` rows, label 0, -1 padding."""
    bboxes = jnp.asarray(bboxes)
    scores = jnp.asarray(scores)
    if scores.shape[1] != 1:
        raise InvalidArgumentError(
            "locality_aware_nms supports one class (the reference op's "
            "documented limit) — use multiclass_nms/matrix_nms otherwise")
    N, M = bboxes.shape[0], bboxes.shape[1]
    K = M if keep_top_k is None or keep_top_k < 0 else min(
        int(keep_top_k), M)

    def merge_pass(boxes, s):
        """Sequential input-order merge (the op relies on EAST's
        row-major box ordering).  carry: (boxes, scores, head, skip)."""

        def step(carry, i):
            bx, sc, head, skip = carry
            iou = iou_similarity(bx[i][None], bx[head][None],
                                 normalized)[0, 0]
            do_merge = (head != i) & (iou > nms_threshold)
            merged = (bx[i] * sc[i] + bx[head] * sc[head]) / jnp.maximum(
                sc[i] + sc[head], _EPS)
            bx = bx.at[head].set(jnp.where(do_merge, merged, bx[head]))
            sc = sc.at[head].set(jnp.where(do_merge, sc[head] + sc[i],
                                           sc[head]))
            # not merged → finalize old head, advance head to i
            skip = skip.at[head].set(jnp.where(do_merge, skip[head], False))
            head = jnp.where(do_merge, head, i)
            return (bx, sc, head, skip), None

        init = (boxes, s, jnp.asarray(0, jnp.int32),
                jnp.ones((M,), bool))
        (bx, sc, head, skip), _ = jax.lax.scan(
            step, init, jnp.arange(M, dtype=jnp.int32))
        skip = skip.at[head].set(False)
        return bx, sc, skip

    def image(boxes, sc):
        bx, s2, skip = merge_pass(boxes, sc[0])
        s2 = jnp.where(skip | (s2 <= score_threshold), -jnp.inf, s2)
        keep = nms(bx, s2, score_threshold=-jnp.inf, nms_top_k=nms_top_k,
                   nms_threshold=nms_threshold, nms_eta=nms_eta,
                   normalized=normalized)
        final = jnp.where(keep & jnp.isfinite(s2), s2, -jnp.inf)
        top_s, top_i = jax.lax.top_k(final, K)
        valid = jnp.isfinite(top_s)
        row = jnp.concatenate([jnp.zeros((K, 1), boxes.dtype),
                               top_s[:, None], bx[top_i]], axis=-1)
        return (jnp.where(valid[:, None], row, -1.0),
                valid.sum().astype(jnp.int32))

    out, nums = jax.vmap(image)(bboxes, scores)
    return out


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    """Density prior boxes (ref: operators/detection/
    density_prior_box_op.h:70-115): per feature-map cell, each
    (fixed_size, density) pair lays a density×density sub-grid of
    centers shifted by ``step_average/density``, one box per
    fixed_ratio, clipped to [0, 1]."""
    H, W = input.shape[2], input.shape[3]
    IH, IW = image.shape[2], image.shape[3]
    densities = [int(d) for d in (densities or [])]
    fixed_sizes = [float(s) for s in (fixed_sizes or [])]
    fixed_ratios = [float(r) for r in (fixed_ratios or [])]
    if not densities or not fixed_sizes or not fixed_ratios:
        raise InvalidArgumentError(
            "density_prior_box needs non-empty densities, fixed_sizes "
            "and fixed_ratios (the reference op requires all three)")
    if len(densities) != len(fixed_sizes):
        raise InvalidArgumentError(
            "densities and fixed_sizes must pair up")
    step_w = float(steps[0]) or IW / W
    step_h = float(steps[1]) or IH / H
    step_avg = int((step_w + step_h) * 0.5)

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w  # [W]
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h  # [H]

    rows = []
    for size, density in zip(fixed_sizes, densities):
        shift = step_avg // density
        for ratio in fixed_ratios:
            bw = size * ratio ** 0.5
            bh = size / ratio ** 0.5
            for di in range(density):
                for dj in range(density):
                    dx = -step_avg / 2.0 + shift / 2.0 + dj * shift
                    dy = -step_avg / 2.0 + shift / 2.0 + di * shift
                    rows.append((dx, dy, bw, bh))
    K = len(rows)
    d = jnp.asarray(rows, jnp.float32)  # [K, 4] (dx, dy, w, h)
    ctr_x = jnp.broadcast_to(cx[None, :, None] + d[:, 0], (H, W, K))
    ctr_y = jnp.broadcast_to(cy[:, None, None] + d[:, 1], (H, W, K))
    boxes = jnp.stack([
        jnp.maximum((ctr_x - d[:, 2] / 2) / IW, 0.0),
        jnp.maximum((ctr_y - d[:, 3] / 2) / IH, 0.0),
        jnp.minimum((ctr_x + d[:, 2] / 2) / IW, 1.0),
        jnp.minimum((ctr_y + d[:, 3] / 2) / IH, 1.0),
    ], axis=-1)  # [H, W, K, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    if flatten_to_2d:
        return boxes.reshape(-1, 4), var.reshape(-1, 4)
    return boxes, var


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_index=False):
    """SSD inference head (ref: fluid/layers/detection.py:620): decode
    location offsets against the priors, then multi-class NMS.  loc
    ``[N, M, 4]``, scores ``[N, M, C]`` → dense ``[N, keep_top_k, 6]``
    (see multiclass_nms for the padding contract; with ``return_index``
    also the kept counts per image — the dense stand-in for the
    reference's index LoD)."""
    decoded = box_coder(prior_box, prior_box_var, jnp.asarray(loc),
                        code_type="decode_center_size")  # [N, M, 4]
    # scores are logits; the reference softmaxes before NMS
    # (detection.py:720) so score_threshold filters probabilities
    probs = jax.nn.softmax(jnp.asarray(scores), axis=-1)
    out, nums = multiclass_nms(
        decoded, jnp.swapaxes(probs, 1, 2), score_threshold,
        nms_top_k, keep_top_k, nms_threshold, True, nms_eta,
        background_label, return_num=True)
    return (out, nums) if return_index else out


def box_clip(input, im_info, name=None):
    """Clip boxes to image bounds (ref kernel operators/detection/
    box_clip_op.h): per image, x into [0, w/scale - 1], y into
    [0, h/scale - 1].  input ``[N, M, 4]``, im_info ``[N, 3]``
    (height, width, scale)."""
    boxes = jnp.asarray(input)
    info = jnp.asarray(im_info, boxes.dtype)
    im_h = jnp.round(info[:, 0] / info[:, 2]) - 1.0
    im_w = jnp.round(info[:, 1] / info[:, 2]) - 1.0
    shape = (-1,) + (1,) * (boxes.ndim - 1)
    zero = jnp.zeros((), boxes.dtype)
    x = jnp.clip(boxes[..., 0::2], zero, im_w.reshape(shape))
    y = jnp.clip(boxes[..., 1::2], zero, im_h.reshape(shape))
    out = jnp.stack([x[..., 0], y[..., 0], x[..., 1], y[..., 1]], axis=-1)
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    """SSD prior (anchor) box generation (ref: operators/detection/
    prior_box_op.h via detection.py:1761).  input ``[N, C, H, W]`` feature
    map, image ``[N, C, IH, IW]`` → (boxes ``[H, W, K, 4]``,
    variances ``[H, W, K, 4]``)."""
    H, W = input.shape[2], input.shape[3]
    IH, IW = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in (
        min_sizes if isinstance(min_sizes, (list, tuple)) else [min_sizes])]
    max_sizes = [float(s) for s in (max_sizes or [])] if not isinstance(
        max_sizes, (int, float)) else [float(max_sizes)]
    ars = [1.0]
    for ar in (aspect_ratios if isinstance(aspect_ratios, (list, tuple))
               else [aspect_ratios]):
        ar = float(ar)
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    step_w = float(steps[0]) or IW / W
    step_h = float(steps[1]) or IH / H

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]

    whs = []
    for k, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if k < len(max_sizes):
                s = (ms * max_sizes[k]) ** 0.5
                whs.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * ar ** 0.5, ms / ar ** 0.5))
        else:
            for ar in ars:
                whs.append((ms * ar ** 0.5, ms / ar ** 0.5))
            if k < len(max_sizes):
                s = (ms * max_sizes[k]) ** 0.5
                whs.append((s, s))
    wh = jnp.asarray(whs, jnp.float32)  # [K, 2]

    boxes = jnp.stack([
        (cxg[..., None] - wh[:, 0] / 2) / IW,
        (cyg[..., None] - wh[:, 1] / 2) / IH,
        (cxg[..., None] + wh[:, 0] / 2) / IW,
        (cyg[..., None] + wh[:, 1] / 2) / IH,
    ], axis=-1)  # [H, W, K, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    return boxes, var


def _roi_batch_ids(rois_num, R, N):
    """rois_num ``[N]`` → per-roi image index ``[R]`` — the dense stand-in
    for the reference's ROI LoD (roi_align_op.h:180-187), computed with a
    static-shape comparison sweep so it jits."""
    if rois_num is None:
        return jnp.zeros((R,), jnp.int32)
    counts = jnp.asarray(rois_num, jnp.int32)
    bounds = jnp.cumsum(counts)  # [N]
    return jnp.sum(jnp.arange(R)[:, None] >= bounds[None, :],
                   axis=1).astype(jnp.int32)


def _bilinear_at(feat, y, x):
    """feat [C, H, W]; y/x same-shape sample grids → [C, *y.shape].
    Transcribes PreCalcForBilinearInterpolate (roi_align_op.h:28-100):
    points outside [-1, H]×[-1, W] contribute 0, in-range points clamp
    low corners into the map."""
    H, W = feat.shape[1], feat.shape[2]
    outside = (y < -1.0) | (y > H) | (x < -1.0) | (x > W)
    y = jnp.clip(y, 0.0, None)
    x = jnp.clip(x, 0.0, None)
    y_low = jnp.clip(jnp.floor(y).astype(jnp.int32), 0, H - 1)
    x_low = jnp.clip(jnp.floor(x).astype(jnp.int32), 0, W - 1)
    y = jnp.where(y_low >= H - 1, jnp.asarray(H - 1, y.dtype), y)
    x = jnp.where(x_low >= W - 1, jnp.asarray(W - 1, x.dtype), x)
    y_high = jnp.clip(y_low + 1, 0, H - 1)
    x_high = jnp.clip(x_low + 1, 0, W - 1)
    ly = (y - y_low).astype(feat.dtype)
    lx = (x - x_low).astype(feat.dtype)
    hy, hx = 1.0 - ly, 1.0 - lx
    v = (feat[:, y_low, x_low] * hy * hx + feat[:, y_low, x_high] * hy * lx
         + feat[:, y_high, x_low] * ly * hx
         + feat[:, y_high, x_high] * ly * lx)
    return jnp.where(outside, jnp.zeros((), feat.dtype), v)


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              name=None):
    """RoI Align (ref: fluid/layers/nn.py:6985 over roi_align_op.h:140):
    average of bilinear samples on a regular grid inside each output bin.

    input ``[N, C, H, W]``, rois ``[R, 4]`` (x1 y1 x2 y2), ``rois_num``
    ``[N]`` mapping rois to images (dense replacement for the ROI LoD;
    omitted → all rois belong to image 0) → ``[R, C, PH, PW]``.

    XLA static-shape note: the reference picks the sample-grid size per
    ROI (``ceil(roi/bin)``) when ``sampling_ratio=-1``; a data-dependent
    grid cannot compile, so -1 maps to the customary fixed grid of 2
    (exact parity when ``sampling_ratio`` is set explicitly).
    """
    x = jnp.asarray(input)
    rois = jnp.asarray(rois, x.dtype)
    R = rois.shape[0]
    if sampling_ratio <= 0:
        import warnings

        warnings.warn(
            "roi_align(sampling_ratio=-1): the reference uses an adaptive "
            "per-ROI ceil(roi/bin) sample grid, which is data-dependent and "
            "cannot compile to a static shape; using a fixed 2x2 grid. Set "
            "sampling_ratio explicitly for exact parity with ported configs.",
            RuntimeWarning, stacklevel=2)
    grid = int(sampling_ratio) if sampling_ratio > 0 else 2
    batch_ids = _roi_batch_ids(rois_num, R, x.shape[0])

    ph_ix = jnp.arange(pooled_height, dtype=x.dtype)
    pw_ix = jnp.arange(pooled_width, dtype=x.dtype)
    g_ix = (jnp.arange(grid, dtype=x.dtype) + 0.5) / grid

    def one(roi, bid):
        xmin, ymin, xmax, ymax = roi * spatial_scale
        rw = jnp.maximum(xmax - xmin, 1.0)
        rh = jnp.maximum(ymax - ymin, 1.0)
        bin_w = rw / pooled_width
        bin_h = rh / pooled_height
        # sample grids: [PH, gh] and [PW, gw]
        ys = ymin + (ph_ix[:, None] + g_ix[None, :]) * bin_h
        xs = xmin + (pw_ix[:, None] + g_ix[None, :]) * bin_w
        yg = jnp.broadcast_to(ys[:, None, :, None],
                              (pooled_height, pooled_width, grid, grid))
        xg = jnp.broadcast_to(xs[None, :, None, :],
                              (pooled_height, pooled_width, grid, grid))
        vals = _bilinear_at(x[bid], yg, xg)  # [C, PH, PW, g, g]
        return vals.mean(axis=(-2, -1))  # [C, PH, PW]

    return jax.vmap(one)(rois, batch_ids)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    """RoI max pooling (ref: fluid/layers/nn.py roi_pool over
    roi_pool_op.h:99-160): integer bin partition of the rounded ROI,
    max per bin, empty bins → 0.  Same dense ``rois_num`` contract as
    roi_align.  → ``[R, C, PH, PW]``."""
    x = jnp.asarray(input)
    rois = jnp.asarray(rois, x.dtype)
    R = rois.shape[0]
    H, W = x.shape[2], x.shape[3]
    batch_ids = _roi_batch_ids(rois_num, R, x.shape[0])
    ph = jnp.arange(pooled_height, dtype=x.dtype)
    pw = jnp.arange(pooled_width, dtype=x.dtype)
    neg_inf = jnp.asarray(-jnp.inf, x.dtype)

    def one(roi, bid):
        x0, y0, x1, y1 = jnp.round(roi * spatial_scale)
        rh = jnp.maximum(y1 - y0 + 1, 1.0)
        rw = jnp.maximum(x1 - x0 + 1, 1.0)
        bin_h = rh / pooled_height
        bin_w = rw / pooled_width
        hstart = jnp.clip(jnp.floor(ph * bin_h) + y0, 0, H)
        hend = jnp.clip(jnp.ceil((ph + 1) * bin_h) + y0, 0, H)
        wstart = jnp.clip(jnp.floor(pw * bin_w) + x0, 0, W)
        wend = jnp.clip(jnp.ceil((pw + 1) * bin_w) + x0, 0, W)
        hgrid = jnp.arange(H, dtype=x.dtype)
        wgrid = jnp.arange(W, dtype=x.dtype)
        mask_h = (hgrid >= hstart[:, None]) & (hgrid < hend[:, None])
        mask_w = (wgrid >= wstart[:, None]) & (wgrid < wend[:, None])
        feat = x[bid]  # [C, H, W]
        tmp = jnp.max(jnp.where(mask_h[:, None, :, None], feat[None], neg_inf),
                      axis=2)  # [PH, C, W]
        out = jnp.max(jnp.where(mask_w[None, None, :, :], tmp[:, :, None, :],
                                neg_inf), axis=3)  # [PH, C, PW]
        out = jnp.where(jnp.isfinite(out), out, 0.0)  # empty bin → 0
        return jnp.transpose(out, (1, 0, 2))  # [C, PH, PW]

    return jax.vmap(one)(rois, batch_ids)


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_num=None, name=None):
    """Position-sensitive RoI average pooling (ref: operators/
    psroi_pool_op.h:82-140, R-FCN): output bin (c, ph, pw) averages the
    dedicated input channel ``(c·PH+ph)·PW+pw`` over the bin's integer
    window; ROI coords are rounded then scaled, bins floor/ceil
    partitioned, empty bins → 0.

    input ``[N, C·PH·PW, H, W]``, rois ``[R, 4]`` (+ dense ``rois_num``)
    → ``[R, C, PH, PW]``."""
    x = jnp.asarray(input)
    rois = jnp.asarray(rois, x.dtype)
    N, Cin, H, W = x.shape
    PH, PW = int(pooled_height), int(pooled_width)
    C = int(output_channels)
    if Cin != C * PH * PW:
        raise InvalidArgumentError(
            f"input channels {Cin} != output_channels·PH·PW = "
            f"{C * PH * PW}")
    R = rois.shape[0]
    batch_ids = _roi_batch_ids(rois_num, R, N)
    xs = x.reshape(N, C, PH, PW, H, W)
    ph = jnp.arange(PH, dtype=x.dtype)
    pw = jnp.arange(PW, dtype=x.dtype)
    hgrid = jnp.arange(H, dtype=x.dtype)
    wgrid = jnp.arange(W, dtype=x.dtype)

    def one(roi, bid):
        x0 = jnp.round(roi[0]) * spatial_scale
        y0 = jnp.round(roi[1]) * spatial_scale
        x1 = (jnp.round(roi[2]) + 1.0) * spatial_scale
        y1 = (jnp.round(roi[3]) + 1.0) * spatial_scale
        rh = jnp.maximum(y1 - y0, 0.1)
        rw = jnp.maximum(x1 - x0, 0.1)
        bh = rh / PH
        bw = rw / PW
        hstart = jnp.clip(jnp.floor(ph * bh + y0), 0, H)
        hend = jnp.clip(jnp.ceil((ph + 1) * bh + y0), 0, H)
        wstart = jnp.clip(jnp.floor(pw * bw + x0), 0, W)
        wend = jnp.clip(jnp.ceil((pw + 1) * bw + x0), 0, W)
        mh = ((hgrid >= hstart[:, None])
              & (hgrid < hend[:, None])).astype(x.dtype)  # [PH, H]
        mw = ((wgrid >= wstart[:, None])
              & (wgrid < wend[:, None])).astype(x.dtype)  # [PW, W]
        sums = jnp.einsum("cpqhw,ph,qw->cpq", xs[bid], mh, mw)
        counts = jnp.einsum("ph,qw->pq", mh, mw)
        return jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), 0.0)

    return jax.vmap(one)(rois, batch_ids)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    """Focal loss for dense detection (ref: fluid/layers/detection.py
    sigmoid_focal_loss over sigmoid_focal_loss_op.h:43-72): x ``[N, C]``
    logits, label ``[N, 1]`` with classes 1..C, 0 = background
    (negative for every class), -1 = ignored; scaled by 1/max(fg_num,1).
    """
    x = jnp.asarray(x)
    label = jnp.asarray(label).reshape(-1, 1)
    C = x.shape[1]
    fg = jnp.maximum(jnp.asarray(fg_num, x.dtype).reshape(()), 1.0)
    d = jnp.arange(C)[None, :]
    c_pos = (label == d + 1).astype(x.dtype)
    c_neg = ((label != -1) & (label != d + 1)).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    # log(p) and log(1-p) in the kernel's stable forms
    term_pos = jnp.power(1.0 - p, gamma) * jnp.log(jnp.maximum(p, 1e-37))
    log1mp = -x * (x >= 0) - jnp.log1p(jnp.exp(x - 2.0 * x * (x >= 0)))
    term_neg = jnp.power(p, gamma) * log1mp
    return -(c_pos * term_pos * (alpha / fg)
             + c_neg * term_neg * ((1.0 - alpha) / fg))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    """Decode a YOLOv3 detection head (ref: fluid/layers/detection.py:1131
    over yolo_box_op.h:30-155).  x ``[N, A*(5+cls), H, W]``, img_size
    ``[N, 2]`` (height, width) → (boxes ``[N, A*H*W, 4]`` corner format,
    scores ``[N, A*H*W, cls]``); predictions below ``conf_thresh`` are
    zeroed, matching the kernel's skip."""
    x = jnp.asarray(x)
    img_size = jnp.asarray(img_size)
    N, _, H, W = x.shape
    A = len(anchors) // 2
    anc = jnp.asarray(anchors, x.dtype).reshape(A, 2)  # (w, h) pairs
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)
    in_h = downsample_ratio * H
    in_w = downsample_ratio * W

    t = x.reshape(N, A, 5 + class_num, H, W)
    img_h = img_size[:, 0].astype(x.dtype).reshape(N, 1, 1, 1)
    img_w = img_size[:, 1].astype(x.dtype).reshape(N, 1, 1, 1)
    grid_x = jnp.arange(W, dtype=x.dtype)
    grid_y = jnp.arange(H, dtype=x.dtype).reshape(-1, 1)

    cx = (grid_x + jax.nn.sigmoid(t[:, :, 0]) * scale + bias) * img_w / W
    cy = (grid_y + jax.nn.sigmoid(t[:, :, 1]) * scale + bias) * img_h / H
    bw = jnp.exp(t[:, :, 2]) * anc[:, 0].reshape(1, A, 1, 1) * img_w / in_w
    bh = jnp.exp(t[:, :, 3]) * anc[:, 1].reshape(1, A, 1, 1) * img_h / in_h
    conf = jax.nn.sigmoid(t[:, :, 4])
    keep = conf >= conf_thresh

    x0, y0 = cx - bw / 2, cy - bh / 2
    x1, y1 = cx + bw / 2, cy + bh / 2
    if clip_bbox:
        x0 = jnp.clip(x0, 0.0, None)
        y0 = jnp.clip(y0, 0.0, None)
        x1 = jnp.minimum(x1, img_w - 1)
        y1 = jnp.minimum(y1, img_h - 1)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1)  # [N, A, H, W, 4]
    boxes = jnp.where(keep[..., None], boxes, 0.0)
    scores = conf[..., None] * jax.nn.sigmoid(
        jnp.moveaxis(t[:, :, 5:], 2, -1))  # [N, A, H, W, cls]
    scores = jnp.where(keep[..., None], scores, 0.0)
    return (boxes.reshape(N, A * H * W, 4),
            scores.reshape(N, A * H * W, class_num))

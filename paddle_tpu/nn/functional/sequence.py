"""Dense sequence ops — the LoD sequence_* family on padded batches.

Parity: python/paddle/fluid/layers/sequence_lod.py (sequence_pool:261,
sequence_expand:638, sequence_enumerate:1235, ...) over
operators/sequence_ops/.  The reference threads ragged sequences
through LoD offsets; the TPU-native convention (SURVEY §7g) is dense
``[B, T, ...]`` batches plus a ``lengths [B]`` tensor — every op here
takes that pair and masks padding exactly where the LoD kernels skipped
it.  ``lengths=None`` means fully-packed rows.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.errors import InvalidArgumentError

__all__ = [
    "sequence_pool", "sequence_softmax", "sequence_reverse",
    "sequence_first_step", "sequence_last_step", "sequence_expand",
    "sequence_expand_as", "sequence_enumerate", "sequence_pad",
    "sequence_unpad", "sequence_concat", "sequence_slice",
    "sequence_scatter", "sequence_reshape",
]


def _mask(x, lengths):
    """[B, T] bool validity mask broadcastable into x [B, T, ...]."""
    B, T = x.shape[0], x.shape[1]
    if lengths is None:
        return jnp.ones((B, T), bool)
    lengths = jnp.asarray(lengths).reshape(B)
    return jnp.arange(T)[None, :] < lengths[:, None]


def _expand_mask(m, x):
    return m.reshape(m.shape + (1,) * (x.ndim - 2))


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  lengths=None):
    """Pool each sequence over its valid time steps (ref:
    sequence_lod.py:261 — average/sum/sqrt/max/last/first).  Empty
    sequences yield ``pad_value``, matching the kernel.  input
    ``[B, T, D]`` → ``[B, D]``."""
    x = jnp.asarray(input)
    m = _expand_mask(_mask(x, lengths), x)
    n = jnp.sum(m, axis=1)  # [B, 1...] valid counts
    pool_type = pool_type.lower()
    if pool_type == "sum":
        out = jnp.sum(jnp.where(m, x, 0), axis=1)
    elif pool_type == "average":
        out = jnp.sum(jnp.where(m, x, 0), axis=1) / jnp.maximum(n, 1)
    elif pool_type == "sqrt":
        out = jnp.sum(jnp.where(m, x, 0), axis=1) / jnp.sqrt(
            jnp.maximum(n, 1).astype(x.dtype))
    elif pool_type == "max":
        out = jnp.max(jnp.where(m, x, -jnp.inf), axis=1)
    elif pool_type == "first":
        out = x[:, 0]
    elif pool_type == "last":
        idx = (jnp.sum(_mask(x, lengths), axis=1) - 1).clip(0)
        out = jnp.take_along_axis(
            x, idx.reshape(-1, 1, *([1] * (x.ndim - 2))), axis=1
        )[:, 0]
    else:
        raise InvalidArgumentError(
            f"pool_type must be one of average/sum/sqrt/max/last/first, "
            f"got {pool_type!r}")
    empty = (n == 0)
    return jnp.where(empty, jnp.asarray(pad_value, x.dtype), out)


def sequence_first_step(input, lengths=None):
    """x[:, 0] (ref: sequence_lod.py sequence_first_step)."""
    return sequence_pool(input, "first", lengths=lengths)


def sequence_last_step(input, lengths=None):
    """x[:, len-1] per row (ref: sequence_lod.py sequence_last_step)."""
    return sequence_pool(input, "last", lengths=lengths)


def sequence_softmax(input, use_cudnn=False, name=None, lengths=None):
    """Softmax over each row's valid steps; padding gets 0 (ref:
    sequence_softmax_op — softmax within each sequence)."""
    x = jnp.asarray(input)
    m = _expand_mask(_mask(x, lengths), x)
    z = jnp.where(m, x, -jnp.inf)
    out = jax.nn.softmax(z, axis=1)
    return jnp.where(m, out, 0.0)


def sequence_reverse(x, name=None, lengths=None):
    """Reverse each row's valid prefix in place, padding untouched (ref:
    sequence_reverse_op).  x ``[B, T, ...]``."""
    x = jnp.asarray(x)
    B, T = x.shape[0], x.shape[1]
    if lengths is None:
        return jnp.flip(x, axis=1)
    lengths = jnp.asarray(lengths).reshape(B)
    t = jnp.arange(T)[None, :]
    src = jnp.where(t < lengths[:, None], lengths[:, None] - 1 - t, t)
    return jnp.take_along_axis(
        x, src.reshape(B, T, *([1] * (x.ndim - 2))), axis=1)


def sequence_expand(x, lengths, name=None):
    """Repeat row i of ``x`` ``lengths[i]`` times along a new time axis
    (dense form of ref sequence_expand :638 — there the repeat counts
    come from y's LoD).  x ``[B, D]`` → ``[B, max(lengths), D]``;
    XLA static shapes make the ragged result a padded batch whose
    validity is the given ``lengths``."""
    x = jnp.asarray(x)
    lengths = jnp.asarray(lengths).reshape(x.shape[0])
    T = int(jnp.max(lengths)) if not isinstance(
        lengths, jax.core.Tracer) else None
    if T is None:
        raise InvalidArgumentError(
            "sequence_expand needs concrete lengths (the output time "
            "axis is max(lengths) — a data-dependent shape under jit); "
            "call it eagerly or use jnp.repeat with a static total")
    # single-tensor return (1.x API shape); validity is the caller's
    # lengths — the padded batch + lengths pair IS the ragged value
    return jnp.broadcast_to(x[:, None], (x.shape[0], T) + x.shape[1:])


def sequence_expand_as(x, y, lengths=None, name=None):
    """Tile each row of ``x`` across y's time axis (ref
    sequence_expand_as): x ``[B, D]``, y ``[B, T, ...]`` →
    ``[B, T, D]``."""
    x = jnp.asarray(x)
    T = jnp.asarray(y).shape[1]
    return jnp.broadcast_to(x[:, None], (x.shape[0], T) + x.shape[1:])


def sequence_enumerate(input, win_size, pad_value=0, name=None,
                       lengths=None):
    """All length-``win_size`` sub-windows per position (ref:
    sequence_lod.py:1235 over sequence_enumerate_op): window j of row i
    is ``x[i, j : j+win]`` padded with ``pad_value`` past the row's
    valid length.  input ``[B, T]`` → ``[B, T, win_size]``."""
    x = jnp.asarray(input)
    if x.ndim == 3 and x.shape[-1] == 1:
        x = x[..., 0]
    B, T = x.shape
    valid = _mask(x, lengths)  # [B, T]
    cols = jnp.arange(T)[:, None] + jnp.arange(win_size)[None, :]  # [T, W]
    in_range = cols < T
    gather = jnp.take(x, jnp.minimum(cols, T - 1), axis=1)  # [B, T, W]
    win_valid = in_range[None] & jnp.take(
        valid, jnp.minimum(cols, T - 1), axis=1)
    # a window starting at an invalid (padding) position is all padding
    win_valid = win_valid & valid[:, :, None]
    return jnp.where(win_valid, gather,
                     jnp.asarray(pad_value, x.dtype))


def sequence_pad(x, pad_value, maxlen=None, name=None, lengths=None):
    """Dense form (ref sequence_pad): the batch is already padded — this
    re-pads to ``maxlen`` (trim or extend) and returns (padded,
    lengths), the reference's output pair."""
    x = jnp.asarray(x)
    B, T = x.shape[0], x.shape[1]
    lengths = (jnp.asarray(lengths).reshape(B) if lengths is not None
               else jnp.full((B,), T))
    target = int(maxlen) if maxlen is not None else T
    pv = jnp.asarray(pad_value, x.dtype)
    if target > T:
        pad_block = jnp.broadcast_to(pv, (B, target - T) + x.shape[2:])
        x = jnp.concatenate([x, pad_block], axis=1)
    elif target < T:
        x = x[:, :target]
    m = jnp.arange(target)[None, :] < jnp.minimum(lengths, target)[:, None]
    x = jnp.where(m.reshape(m.shape + (1,) * (x.ndim - 2)), x, pv)
    return x, jnp.minimum(lengths, target)


def sequence_unpad(x, length, name=None):
    """Zero out the padding region and return the batch with its lengths
    (ref sequence_unpad flattens to LoD; dense form keeps ``[B, T]`` +
    lengths as THE ragged representation)."""
    x = jnp.asarray(x)
    m = _expand_mask(_mask(x, length), x)
    return jnp.where(m, x, 0)  # single-tensor return, 1.x API shape


def sequence_slice(input, offset, length, name=None):
    """Per-row sub-sequence extraction (ref: sequence_slice_op): row i
    keeps ``input[i, offset[i] : offset[i]+length]``.  Dense form:
    ``length`` is a shared static width (XLA static shapes); ragged
    per-row lengths stay ragged via a lengths tensor downstream."""
    x = jnp.asarray(input)
    B, T = x.shape[0], x.shape[1]
    offset = jnp.asarray(offset).reshape(B)
    if not isinstance(length, int):
        L = jnp.asarray(length).reshape(-1)
        if isinstance(L, jax.core.Tracer) or L.shape[0] != 1:
            raise InvalidArgumentError(
                "dense sequence_slice needs one static window length "
                "(the output time axis); keep per-row raggedness via a "
                "lengths tensor instead")
        length = int(L[0])
    if not isinstance(offset, jax.core.Tracer):
        import numpy as _np

        off_np = _np.asarray(offset)
        if (off_np < 0).any() or (off_np + length > T).any():
            raise InvalidArgumentError(
                f"sequence_slice window [offset, offset+{length}) leaves "
                f"the time axis of length {T} (the reference op enforces "
                f"offset+length <= seq_len)")
    idx = offset[:, None] + jnp.arange(length)[None, :]  # [B, L]
    in_range = (idx >= 0) & (idx < T)
    gathered = jnp.take_along_axis(
        x, jnp.clip(idx, 0, T - 1).reshape(B, length,
                                           *([1] * (x.ndim - 2))), axis=1)
    # under trace an OOB window can't raise — zero the escaped positions
    # so the padding is visible, not duplicated frames
    return jnp.where(in_range.reshape(B, length, *([1] * (x.ndim - 2))),
                     gathered, 0)


def sequence_scatter(input, index, updates, lengths=None, name=None):
    """Scatter-add per-row updates at per-row positions (ref:
    sequence_scatter_op: out = input; out[i, index_row_i] += updates).
    Dense form: index ``[B, K]`` positions into each row, updates
    ``[B, K, ...]``; entries past ``lengths`` (of the K axis) are
    dropped."""
    x = jnp.asarray(input)
    B, T = x.shape[0], x.shape[1]
    index = jnp.asarray(index).astype(jnp.int32)
    updates = jnp.asarray(updates, x.dtype)
    K = index.shape[1]
    if lengths is not None:
        valid = jnp.arange(K)[None, :] < jnp.asarray(lengths).reshape(B, 1)
        index = jnp.where(valid, index, T)  # OOB → dropped
    bidx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, K))
    return x.at[bidx, index].add(updates, mode="drop")


def sequence_reshape(input, new_dim, lengths=None, name=None):
    """Re-chunk each row's features (ref: sequence_reshape_op: row
    timesteps re-split so the feature width becomes ``new_dim``; row
    lengths scale by D/new_dim).  input ``[B, T, D]`` with
    ``T·D % new_dim == 0`` → (``[B, T·D/new_dim, new_dim]``, scaled
    lengths)."""
    x = jnp.asarray(input)
    B, T, D = x.shape[0], x.shape[1], x.shape[2]
    if (T * D) % new_dim:
        raise InvalidArgumentError(
            f"T·D = {T * D} not divisible by new_dim {new_dim}")
    out = x.reshape(B, T * D // new_dim, new_dim)
    if lengths is None:
        return out
    lengths = jnp.asarray(lengths).reshape(B)
    if (D % new_dim) and (new_dim % D):
        raise InvalidArgumentError(
            f"per-row rescaling needs D ({D}) and new_dim ({new_dim}) "
            f"divisible one way or the other")
    if not isinstance(lengths, jax.core.Tracer):
        import numpy as _np

        if (_np.asarray(lengths) * D % new_dim).any():
            raise InvalidArgumentError(
                f"a row's valid elements (lengths·{D}) are not divisible "
                f"by new_dim {new_dim} — the reference op rejects this "
                f"(sequence_reshape_op) rather than dropping data")
    new_len = lengths * D // new_dim
    return out, new_len


def sequence_concat(input, lengths=None, name=None):
    """Concatenate sequences row-wise along time (ref sequence_concat:
    per-row LoD concat).  input: list of ``[B, Ti, ...]`` batches (+
    optional list of lengths) → (``[B, ΣTi, ...]``, lengths).  With
    full rows this is jnp.concatenate; ragged rows compact each row's
    valid prefixes together."""
    xs = [jnp.asarray(x) for x in input]
    if lengths is None:
        return jnp.concatenate(xs, axis=1)
    B = xs[0].shape[0]
    total_T = sum(x.shape[1] for x in xs)
    lens = [jnp.asarray(l).reshape(B) for l in lengths]
    # scatter each piece's valid prefix at its per-row offset
    out = jnp.zeros((B, total_T) + xs[0].shape[2:], xs[0].dtype)
    offset = jnp.zeros((B,), jnp.int32)
    for x, l in zip(xs, lens):
        T = x.shape[1]
        t = jnp.arange(T)[None, :]
        dest = offset[:, None] + t  # [B, T]
        valid = t < l[:, None]
        dest = jnp.where(valid, dest, total_T)  # drop padding (OOB)
        bidx = jnp.broadcast_to(jnp.arange(B)[:, None], dest.shape)
        out = out.at[bidx, dest].set(x, mode="drop")
        offset = offset + l.astype(jnp.int32)
    return out  # single-tensor return; row lengths = sum of input lengths

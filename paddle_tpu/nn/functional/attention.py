"""Attention ops.

``scaled_dot_product_attention`` is the public entry (paddle 2.x API parity;
the reference era predates flash attention — SURVEY §5 marks long-context as
a new capability).  On TPU the hot path routes to the Pallas flash-attention
kernel in ``paddle_tpu.ops`` when shapes/dtypes allow; otherwise an XLA
composite (softmax(QK^T)V) that the compiler fuses.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["scaled_dot_product_attention"]


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None, rng_key=None, use_pallas=None):
    """query/key/value: (batch, seq, heads, head_dim) — paddle layout.

    Routes to the Pallas TPU flash kernel for long sequences; XLA path
    otherwise.  Returns (batch, seq, heads, head_dim).
    """
    q = jnp.asarray(query)
    k = jnp.asarray(key)
    v = jnp.asarray(value)

    if use_pallas is None:
        use_pallas = False
        try:
            # gate threshold measured in-model on v5e: XLA's fused bf16
            # attention is flash-class, so the kernel only engages where
            # it doesn't lose (parity at seq >= 4096, with O(S) memory)
            if (jax.default_backend() == "tpu" and attn_mask is None
                    and dropout_p == 0.0 and q.shape[1] >= 4096
                    and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
                    and q.shape[-1] in (64, 128, 256)):
                from ...ops import flash_attention as _  # noqa: F401

                use_pallas = True
        except ImportError:
            use_pallas = False
    if use_pallas:
        from ...ops.flash_attention import flash_attention

        # pallas kernel uses (batch, heads, seq, dim)
        out = flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=is_causal)
        return out.transpose(0, 2, 1, 3)

    scale = 1.0 / math.sqrt(q.shape[-1])
    # (b, s, h, d) → (b, h, s, d)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    logits = jnp.matmul(qt, kt.transpose(0, 1, 3, 2),
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        logits = jnp.where(causal_mask, logits, -1e30)
    if attn_mask is not None:
        m = jnp.asarray(attn_mask)
        if m.dtype == jnp.bool_:
            logits = jnp.where(m, logits, -1e30)
        else:
            logits = logits + m.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        from .common import dropout as _dropout

        probs = _dropout(probs, p=dropout_p, training=True, key=rng_key)
    out = jnp.matmul(probs, vt, preferred_element_type=jnp.float32).astype(q.dtype)
    return out.transpose(0, 2, 1, 3)

"""Misc 1.x op promotions: the dense long tail of fluid.layers.

Each function transcribes its reference kernel (cited per-op) — these are
names that previously resolved as hint-shims; they are small dense
computations with clean TPU formulations, so they get real
implementations.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.errors import InvalidArgumentError

__all__ = [
    "adaptive_pool2d", "adaptive_pool3d", "add_position_encoding",
    "affine_channel", "bpr_loss", "rank_loss", "margin_rank_loss",
    "shuffle_channel", "space_to_depth", "fsp_matrix",
    "continuous_value_model", "sampling_id",
    "fill_constant_batch_size_like", "gaussian_random_batch_size_like",
    "uniform_random_batch_size_like", "lrn", "im2sequence",
]


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """1.x adaptive pool (ref: fluid/layers/nn.py adaptive_pool2d) over the
    2.0 functional ops."""
    from . import pooling as P

    if pool_type == "max":
        if require_index:
            return P.adaptive_max_pool2d(input, pool_size, return_mask=True)
        return P.adaptive_max_pool2d(input, pool_size)
    if pool_type == "avg":
        if require_index:
            raise InvalidArgumentError("require_index only with max pooling")
        return P.adaptive_avg_pool2d(input, pool_size)
    raise InvalidArgumentError(f"pool_type must be max/avg, got {pool_type!r}")


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    from . import pooling as P

    if pool_type == "max":
        if require_index:
            return P.adaptive_max_pool3d(input, pool_size, return_mask=True)
        return P.adaptive_max_pool3d(input, pool_size)
    if pool_type == "avg":
        if require_index:
            raise InvalidArgumentError("require_index only with max pooling")
        return P.adaptive_avg_pool3d(input, pool_size)
    raise InvalidArgumentError(f"pool_type must be max/avg, got {pool_type!r}")


def add_position_encoding(input, alpha, beta, name=None):
    """out = alpha·x + beta·sinusoid (ref: add_position_encoding_op.h:77):
    channel k < E/2 gets sin(pos / 10000^(k/(E/2−1))), channel E/2+k the
    matching cos.  input ``[N, S, E]``."""
    x = jnp.asarray(input)
    if x.ndim != 3:
        raise InvalidArgumentError(
            f"add_position_encoding wants [N, S, E], got {x.shape}")
    N, S, E = x.shape
    if E % 2:
        raise InvalidArgumentError(
            f"add_position_encoding needs an even feature size, got {E} "
            "(the encoding pairs sin/cos channels)")
    half = E // 2
    pos = jnp.arange(S, dtype=jnp.float32)[:, None]            # [S, 1]
    k = jnp.arange(half, dtype=jnp.float32)[None, :]           # [1, half]
    denom = jnp.where(half > 1,
                      jnp.power(10000.0, k / jnp.maximum(half - 1, 1)),
                      10000.0)
    val = pos / denom                                          # [S, half]
    enc = jnp.concatenate([jnp.sin(val), jnp.cos(val)], axis=1)  # [S, E]
    return (x * alpha + enc[None].astype(x.dtype) * beta).astype(x.dtype)


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", act=None,
                   name=None):
    """Per-channel y = scale·x + bias (ref: affine_channel_op.cc)."""
    x = jnp.asarray(x)
    ch_axis = 1 if data_layout == "NCHW" else x.ndim - 1
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    out = x
    if scale is not None:
        out = out * jnp.asarray(scale, x.dtype).reshape(shape)
    if bias is not None:
        out = out + jnp.asarray(bias, x.dtype).reshape(shape)
    if act == "relu":
        out = jax.nn.relu(out)
    elif act is not None:
        raise InvalidArgumentError(f"unsupported act {act!r}")
    return out


def bpr_loss(input, label, name=None):
    """Bayesian personalized ranking loss (ref: bpr_loss_op.h:52):
    loss_i = −(1/(D−1)) Σ_{j≠y_i} log σ(x_iy − x_ij).  input ``[N, D]``
    logits, label ``[N, 1]`` int → ``[N, 1]``."""
    x = jnp.asarray(input, jnp.float32)
    y = jnp.asarray(label).reshape(-1).astype(jnp.int32)
    N, D = x.shape
    pos = jnp.take_along_axis(x, y[:, None], axis=1)           # [N, 1]
    # −log σ(pos − neg) = softplus(neg − pos); softplus is the
    # overflow-stable form (log1p(exp(·)) infs past ~88)
    sp = jax.nn.softplus(x - pos)                              # [N, D]
    mask = jax.nn.one_hot(y, D, dtype=bool)
    total = jnp.sum(jnp.where(mask, 0.0, sp), axis=1)
    return (total / (D - 1))[:, None].astype(jnp.asarray(input).dtype)


def rank_loss(label, left, right, name=None):
    """RankNet loss (ref: rank_loss_op.h:40):
    out = log(1 + exp(l − r)) − label·(l − r)."""
    lbl = jnp.asarray(label, jnp.float32)
    l = jnp.asarray(left, jnp.float32)
    r = jnp.asarray(right, jnp.float32)
    return jax.nn.softplus(l - r) - lbl * (l - r)  # overflow-stable log1p-exp


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """out = relu(−label·(l − r) + margin) (ref: margin_rank_loss_op.h:60)."""
    lbl = jnp.asarray(label, jnp.float32)
    l = jnp.asarray(left, jnp.float32)
    r = jnp.asarray(right, jnp.float32)
    return jax.nn.relu(-lbl * (l - r) + margin)


def shuffle_channel(x, group, name=None):
    """Channel shuffle (ref: shuffle_channel_op.h): [N, C, H, W] with C =
    g·n → regroup channels (g, n) → (n, g)."""
    x = jnp.asarray(x)
    N, C, H, W = x.shape
    if C % group:
        raise InvalidArgumentError(f"channels {C} not divisible by {group}")
    return (x.reshape(N, group, C // group, H, W)
            .transpose(0, 2, 1, 3, 4).reshape(N, C, H, W))


def space_to_depth(x, blocksize, name=None):
    """Rearrange spatial blocks into channels (ref: space_to_depth_op.h:41):
    out[b, off·C + c, j, i] = in[b, c, j·bs + off//bs, i·bs + off%bs] —
    offset-major channel order.  [N, C, H, W] → [N, C·bs², H/bs, W/bs]."""
    x = jnp.asarray(x)
    bs = int(blocksize)
    N, C, H, W = x.shape
    if H % bs or W % bs:
        raise InvalidArgumentError(
            f"spatial dims {(H, W)} not divisible by blocksize {bs}")
    # [N, C, H/bs, bs, W/bs, bs] → offsets (bh, bw) lead the channel dim
    r = x.reshape(N, C, H // bs, bs, W // bs, bs)
    r = r.transpose(0, 3, 5, 1, 2, 4)          # [N, bh, bw, C, H/bs, W/bs]
    return r.reshape(N, C * bs * bs, H // bs, W // bs)


def fsp_matrix(x, y, name=None):
    """Flow-of-solution-procedure matrix (ref: fsp_op.h:31): the
    H·W-normalized gram between two feature maps —
    out[n, i, j] = (1/(H·W)) Σ_hw x[n,i,h,w]·y[n,j,h,w]."""
    xf = jnp.asarray(x, jnp.float32)
    yf = jnp.asarray(y, jnp.float32)
    H, W = xf.shape[2], xf.shape[3]
    out = jnp.einsum("nihw,njhw->nij", xf, yf) / (H * W)
    return out.astype(jnp.asarray(x).dtype)


def continuous_value_model(input, cvm, use_cvm=True, name=None):
    """CTR show/click feature transform (ref: cvm_op.h:26): with
    ``use_cvm`` the first two columns become log(show+1) and
    log(click+1)−log(show+1); without it they are dropped."""
    x = jnp.asarray(input, jnp.float32)
    if use_cvm:
        c0 = jnp.log1p(x[:, 0:1])
        c1 = jnp.log1p(x[:, 1:2]) - c0
        return jnp.concatenate([c0, c1, x[:, 2:]], axis=1)
    return x[:, 2:]


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64", name=None):
    """Sample one index per row from the probability rows of ``x``
    (ref: sampling_id_op.h): r ~ U[min, max), index = first cumsum ≥ r."""
    from ...framework import random as _random

    xf = jnp.asarray(x, jnp.float32)
    key = (jax.random.PRNGKey(seed) if seed else _random.split_key())
    r = jax.random.uniform(key, (xf.shape[0],), minval=float(min),
                           maxval=float(max))
    cum = jnp.cumsum(xf, axis=1)
    idx = jnp.sum((cum < r[:, None]).astype(jnp.int32), axis=1)
    return jnp.clip(idx, 0, xf.shape[1] - 1).astype(dtype)


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  force_cpu=False):
    """shape with dim ``output_dim_idx`` taken from input's
    ``input_dim_idx`` (ref: fill_constant_batch_size_like_op.cc)."""
    shape = list(shape)
    shape[output_dim_idx] = jnp.asarray(input).shape[input_dim_idx]
    return jnp.full(tuple(shape), value, dtype=dtype)


def uniform_random_batch_size_like(input, shape, dtype="float32", min=-1.0,
                                   max=1.0, seed=0, input_dim_idx=0,
                                   output_dim_idx=0):
    from ...framework import random as _random

    shape = list(shape)
    shape[output_dim_idx] = jnp.asarray(input).shape[input_dim_idx]
    key = jax.random.PRNGKey(seed) if seed else _random.split_key()
    return jax.random.uniform(key, tuple(shape), minval=float(min),
                              maxval=float(max)).astype(dtype)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    from ...framework import random as _random

    shape = list(shape)
    shape[output_dim_idx] = jnp.asarray(input).shape[input_dim_idx]
    key = jax.random.PRNGKey(seed) if seed else _random.split_key()
    return (jax.random.normal(key, tuple(shape)) * std + mean).astype(dtype)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    """1.x local response norm wrapper over the 2.0 functional (size=n)."""
    from .norm import local_response_norm

    return local_response_norm(input, size=n, alpha=alpha, beta=beta, k=k,
                               data_format=data_format)


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size
                =None, out_stride=1, name=None):
    """Sliding-window patch extraction (ref: im2sequence_op.h over the
    kOCF im2col): [N, C, H, W] → [N·OH·OW, C·fh·fw] rows in output-
    position order, columns channel-major (c, fh, fw).  The dense form
    returns [N, OH·OW, C·fh·fw] (the LoD over images is the leading dim);
    the ragged ``input_image_size``/``out_stride`` branch is not
    supported — pad upstream."""
    if input_image_size is not None:
        raise InvalidArgumentError(
            "im2sequence: per-image sizes are LoD machinery; pad upstream")
    x = jnp.asarray(input)
    fh, fw = ((filter_size, filter_size)
              if isinstance(filter_size, int) else tuple(filter_size))
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    if isinstance(padding, int):
        pu = pd_ = pl = pr = padding
    elif len(padding) == 2:
        pu, pl = padding
        pd_, pr = padding
    else:
        pu, pl, pd_, pr = padding
    N, C, H, W = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (pu, pd_), (pl, pr)))
    patches = jax.lax.conv_general_dilated_patches(
        xp, (fh, fw), (sh, sw), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # feature dim is C*fh*fw channel-major — exactly kOCF's column order
    Np, F, OH, OW = patches.shape
    return patches.reshape(N, F, OH * OW).transpose(0, 2, 1)

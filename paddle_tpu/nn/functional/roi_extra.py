"""RoI long-tail ops: precise/deformable pooling, perspective transform,
EAST geometry decode.

Capability parity (reference):
  prroi_pool               fluid/layers/nn.py:13800 over prroi_pool_op.h
  deformable_roi_pooling   fluid/layers/nn.py:14586 over
                           deformable_psroi_pooling_op.h
  roi_perspective_transform  fluid/layers/detection.py:2498 over
                           detection/roi_perspective_transform_op.cc
  polygon_box_transform    detection/polygon_box_transform_op.cc

Dense TPU design: every op is a vmapped closed-form computation — PrRoI's
exact bilinear integral becomes two separable weight matrices and one
einsum per RoI (MXU work, no sample loops); the sampling ops reuse the
package's clamped bilinear gather.  ``rois_num`` per-image counts follow
the module-wide dense-LoD convention of :mod:`.detection`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.errors import InvalidArgumentError
from .detection import _roi_batch_ids

__all__ = ["prroi_pool", "deformable_roi_pooling",
           "roi_perspective_transform", "polygon_box_transform"]


def _hat_integral(p, a, b):
    """∫_a^b max(0, 1-|x-p|) dx for pixel centers p (vector) over a scalar
    window — the exact bilinear (hat basis) integral PrRoI pooling is
    built on (prroi_pool_op.h PrRoIPoolingMatCalculation, in closed form
    instead of per-corner case analysis)."""
    def anti(u):  # ∫_{-1}^{u} (1-|v|)dv with u clamped to [-1, 1]
        u = jnp.clip(u, -1.0, 1.0)
        neg = 0.5 * (u + 1.0) ** 2
        pos = 0.5 + u - 0.5 * u * u
        return jnp.where(u <= 0, neg, pos)

    return anti(b - p) - anti(a - p)


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    """Precise RoI pooling (ref: nn.py:13800 over prroi_pool_op.h): each
    output bin is the EXACT integral of the bilinearly-interpolated
    feature over the bin window divided by the window area — no sampling
    grid, fully differentiable in the roi coordinates too.

    input ``[N, C, H, W]``, rois ``[R, 4]``, ``batch_roi_nums [N]``
    (dense LoD stand-in; omitted → all rois on image 0) →
    ``[R, C, PH, PW]``.
    """
    x = jnp.asarray(input)
    rois = jnp.asarray(rois, jnp.float32)
    N, C, H, W = x.shape
    R = rois.shape[0]
    PH, PW = int(pooled_height), int(pooled_width)
    batch_ids = _roi_batch_ids(batch_roi_nums, R, N)

    py = jnp.arange(H, dtype=jnp.float32)
    px = jnp.arange(W, dtype=jnp.float32)

    def one(roi, bid):
        x0, y0, x1, y1 = roi * spatial_scale
        rw = jnp.maximum(x1 - x0, 0.0)
        rh = jnp.maximum(y1 - y0, 0.0)
        bw = rw / PW
        bh = rh / PH
        win = bw * bh
        # separable integral weights: Wy [PH, H], Wx [PW, W]
        ys = y0 + jnp.arange(PH, dtype=jnp.float32) * bh
        xs = x0 + jnp.arange(PW, dtype=jnp.float32) * bw
        Wy = jax.vmap(lambda a: _hat_integral(py, a, a + bh))(ys)
        Wx = jax.vmap(lambda a: _hat_integral(px, a, a + bw))(xs)
        feat = x[bid].astype(jnp.float32)        # [C, H, W]
        out = jnp.einsum("ph,qw,chw->cpq", Wy, Wx, feat)
        return jnp.where(win > 0, out / jnp.maximum(win, 1e-12), 0.0)

    out = jax.vmap(one)(rois, batch_ids)
    return out.astype(x.dtype)


def _bilinear_clamped(feat, h, w):
    """Pointwise bilinear with the deformable-psroi border convention
    (deformable_psroi_pooling_op.h bilinear_interp): coordinates already
    clamped into [0, H-1]x[0, W-1] by the caller."""
    H, W = feat.shape
    h0 = jnp.clip(jnp.floor(h).astype(jnp.int32), 0, H - 1)
    w0 = jnp.clip(jnp.floor(w).astype(jnp.int32), 0, W - 1)
    h1 = jnp.clip(h0 + 1, 0, H - 1)
    w1 = jnp.clip(w0 + 1, 0, W - 1)
    lh = h - h0
    lw = w - w0
    v00 = feat[h0, w0]
    v01 = feat[h0, w1]
    v10 = feat[h1, w0]
    v11 = feat[h1, w1]
    top = v00 + (v01 - v00) * lw
    bot = v10 + (v11 - v10) * lw
    return top + (bot - top) * lh


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1,
                           position_sensitive=False, rois_num=None,
                           name=None):
    """Deformable (PS-)RoI pooling (ref: nn.py:14586 over
    deformable_psroi_pooling_op.h): average of bilinear samples on a grid
    displaced by learned per-part offsets ``trans``.

    input ``[N, C, H, W]``; rois ``[R, 4]``; trans
    ``[R, 2*num_classes? , part_h, part_w]`` (ignored when ``no_trans``);
    ``position_sensitive`` divides channels by PH*PW (R-FCN style) →
    output ``[R, C', PH, PW]``.
    """
    x = jnp.asarray(input)
    rois = jnp.asarray(rois, jnp.float32)
    N, C, H, W = x.shape
    R = rois.shape[0]
    PH, PW = int(pooled_height), int(pooled_width)
    gh, gw = int(group_size[0]), int(group_size[1])
    if part_size is None:
        part_h, part_w = PH, PW
    else:
        part_h, part_w = int(part_size[0]), int(part_size[1])
    sp = int(sample_per_part)
    if position_sensitive:
        if C % (PH * PW):
            raise InvalidArgumentError(
                f"position_sensitive: channels {C} not divisible by "
                f"{PH}*{PW}")
        out_dim = C // (PH * PW)
    else:
        if gh != 1 or gw != 1:
            raise InvalidArgumentError(
                "group_size != [1, 1] requires position_sensitive=True "
                "(the channel group indexing is PS-RoI's)")
        out_dim = C
    batch_ids = _roi_batch_ids(rois_num, R, N)
    if not no_trans:
        trans = jnp.asarray(trans, jnp.float32)
        num_classes = trans.shape[1] // 2
        channels_each_class = max(out_dim // num_classes, 1)
    else:
        num_classes, channels_each_class = 1, out_dim

    ph_ix = jnp.arange(PH)
    pw_ix = jnp.arange(PW)
    ct_ix = jnp.arange(out_dim)

    def one(roi, bid, tr):
        # the kernel rounds roi corners to ints then recenters by 0.5
        x0 = jnp.round(roi[0]) * spatial_scale - 0.5
        y0 = jnp.round(roi[1]) * spatial_scale - 0.5
        x1 = (jnp.round(roi[2]) + 1.0) * spatial_scale - 0.5
        y1 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x1 - x0, 0.1)
        rh = jnp.maximum(y1 - y0, 0.1)
        bw = rw / PW
        bh = rh / PH
        sub_w = bw / sp
        sub_h = bh / sp
        feat = x[bid].astype(jnp.float32)

        def bin_val(ctop, ph, pw):
            part_hi = jnp.floor(ph / PH * part_h).astype(jnp.int32)
            part_wi = jnp.floor(pw / PW * part_w).astype(jnp.int32)
            class_id = ctop // channels_each_class
            if no_trans:
                tx = jnp.float32(0.0)
                ty = jnp.float32(0.0)
            else:
                tx = tr[2 * class_id, part_hi, part_wi] * trans_std
                ty = tr[2 * class_id + 1, part_hi, part_wi] * trans_std
            wstart = pw * bw + x0 + tx * rw
            hstart = ph * bh + y0 + ty * rh
            if position_sensitive:
                g_w = jnp.clip(jnp.floor(pw * gw / PW).astype(jnp.int32),
                               0, gw - 1)
                g_h = jnp.clip(jnp.floor(ph * gh / PH).astype(jnp.int32),
                               0, gh - 1)
                c = (ctop * gh + g_h) * gw + g_w
            else:
                c = ctop
            iw = jnp.arange(sp, dtype=jnp.float32)
            ww = wstart + iw * sub_w                    # [sp]
            hh = hstart + iw * sub_h                    # [sp]
            wg, hg = jnp.meshgrid(ww, hh)
            ok = ((wg >= -0.5) & (wg <= W - 0.5)
                  & (hg >= -0.5) & (hg <= H - 0.5))
            wc = jnp.clip(wg, 0.0, W - 1.0)
            hc = jnp.clip(hg, 0.0, H - 1.0)
            vals = _bilinear_clamped(feat[c], hc, wc)
            cnt = ok.sum()
            return jnp.where(cnt > 0,
                             jnp.sum(jnp.where(ok, vals, 0.0))
                             / jnp.maximum(cnt, 1), 0.0)

        f = jax.vmap(jax.vmap(jax.vmap(bin_val, in_axes=(None, None, 0)),
                              in_axes=(None, 0, None)),
                     in_axes=(0, None, None))
        return f(ct_ix, ph_ix, pw_ix)

    tr_in = (jnp.zeros((R, 2, part_h, part_w), jnp.float32)
             if no_trans else trans)
    out = jax.vmap(one)(rois, batch_ids, tr_in)
    return out.astype(x.dtype)


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_num=None, name=None):
    """Warp quadrilateral RoIs to a fixed rectangle by perspective
    transform (ref: detection.py:2498 over
    roi_perspective_transform_op.cc).

    input ``[N, C, H, W]``; rois ``[R, 8]`` as (x1 y1 x2 y2 x3 y3 x4 y4)
    clockwise from top-left → (out ``[R, C, TH, TW]``, mask
    ``[R, 1, TH, TW]`` int32, transform_matrix ``[R, 9]``).
    """
    x = jnp.asarray(input)
    rois = jnp.asarray(rois, jnp.float32)
    N, C, H, W = x.shape
    R = rois.shape[0]
    TH, TW = int(transformed_height), int(transformed_width)
    batch_ids = _roi_batch_ids(rois_num, R, N)

    def matrix_for(rx, ry):
        """get_transform_matrix (op.cc:110) verbatim semantics."""
        x0, x1, x2, x3 = rx[0], rx[1], rx[2], rx[3]
        y0, y1, y2, y3 = ry[0], ry[1], ry[2], ry[3]
        len1 = jnp.sqrt((x0 - x1) ** 2 + (y0 - y1) ** 2)
        len2 = jnp.sqrt((x1 - x2) ** 2 + (y1 - y2) ** 2)
        len3 = jnp.sqrt((x2 - x3) ** 2 + (y2 - y3) ** 2)
        len4 = jnp.sqrt((x3 - x0) ** 2 + (y3 - y0) ** 2)
        est_h = (len2 + len4) / 2.0
        est_w = (len1 + len3) / 2.0
        norm_h = jnp.float32(max(2, TH))
        norm_w = jnp.round(est_w * (norm_h - 1)
                           / jnp.maximum(est_h, 1e-5)) + 1
        norm_w = jnp.clip(norm_w, 2, TW)
        dx1 = x1 - x2
        dx2 = x3 - x2
        dx3 = x0 - x1 + x2 - x3
        dy1 = y1 - y2
        dy2 = y3 - y2
        dy3 = y0 - y1 + y2 - y3
        den = dx1 * dy2 - dx2 * dy1 + 1e-5
        m6 = (dx3 * dy2 - dx2 * dy3) / den / (norm_w - 1)
        m7 = (dx1 * dy3 - dx3 * dy1) / den / (norm_h - 1)
        m8 = jnp.float32(1.0)
        m3 = (y1 - y0 + m6 * (norm_w - 1) * y1) / (norm_w - 1)
        m4 = (y3 - y0 + m7 * (norm_h - 1) * y3) / (norm_h - 1)
        m5 = y0
        m0 = (x1 - x0 + m6 * (norm_w - 1) * x1) / (norm_w - 1)
        m1 = (x3 - x0 + m7 * (norm_h - 1) * x3) / (norm_h - 1)
        m2 = x0
        return jnp.stack([m0, m1, m2, m3, m4, m5, m6, m7, m8])

    def in_quad(px_, py_, rx, ry):
        """Point-in-quadrilateral with the kernel's 1e-4 edge tolerance
        (op.cc:46): on-edge points count as inside."""
        on_edge = jnp.zeros_like(px_, bool)
        n_cross = jnp.zeros_like(px_, jnp.int32)
        for i in range(4):
            xs, ys = rx[i], ry[i]
            xe, ye = rx[(i + 1) % 4], ry[(i + 1) % 4]
            horiz = jnp.abs(ys - ye) < 1e-4
            on_h = (horiz & (jnp.abs(py_ - ys) < 1e-4)
                    & (px_ >= jnp.minimum(xs, xe) - 1e-4)
                    & (px_ <= jnp.maximum(xs, xe) + 1e-4))
            ix = (py_ - ys) * (xe - xs) / jnp.where(horiz, 1.0, ye - ys) + xs
            on_e = (~horiz & (jnp.abs(ix - px_) < 1e-4)
                    & (py_ >= jnp.minimum(ys, ye) - 1e-4)
                    & (py_ <= jnp.maximum(ys, ye) + 1e-4))
            on_edge = on_edge | on_h | on_e
            crossing = (~horiz
                        & (py_ > jnp.minimum(ys, ye) + 1e-4)
                        & (py_ <= jnp.maximum(ys, ye) + 1e-4)
                        & (ix > px_))
            n_cross = n_cross + crossing.astype(jnp.int32)
        return on_edge | (n_cross % 2 == 1)

    ow = jnp.arange(TW, dtype=jnp.float32)
    oh = jnp.arange(TH, dtype=jnp.float32)
    owg, ohg = jnp.meshgrid(ow, oh)          # [TH, TW]

    def one(roi, bid):
        rx = roi[0::2] * spatial_scale
        ry = roi[1::2] * spatial_scale
        m = matrix_for(rx, ry)
        u = m[0] * owg + m[1] * ohg + m[2]
        v = m[3] * owg + m[4] * ohg + m[5]
        w = m[6] * owg + m[7] * ohg + m[8]
        in_w = u / w
        in_h = v / w
        inside_q = in_quad(in_w, in_h, rx, ry)
        in_range = ((in_w > -0.5) & (in_w < W - 0.5)
                    & (in_h > -0.5) & (in_h < H - 0.5))
        valid = inside_q & in_range
        wc = jnp.clip(in_w, 0.0, W - 1.0)
        hc = jnp.clip(in_h, 0.0, H - 1.0)
        feat = x[bid].astype(jnp.float32)    # [C, H, W]
        vals = jax.vmap(lambda fc: _bilinear_clamped(fc, hc, wc))(feat)
        out = jnp.where(valid[None], vals, 0.0)
        return out, valid.astype(jnp.int32)[None], m

    out, mask, mats = jax.vmap(one)(rois, batch_ids)
    return out.astype(x.dtype), mask, mats


def polygon_box_transform(input, name=None):
    """EAST geometry decode (ref: polygon_box_transform_op.cc): turn
    per-pixel offset channels into absolute quad coordinates on the 4x
    downsampled grid — even channels become ``4*w - v``, odd channels
    ``4*h - v``.  input ``[N, G, H, W]`` (G even) → same shape.
    """
    x = jnp.asarray(input)
    if x.ndim != 4 or x.shape[1] % 2:
        raise InvalidArgumentError(
            f"polygon_box_transform wants [N, 2k, H, W], got {x.shape}")
    N, G, H, W = x.shape
    wpos = 4.0 * jnp.arange(W, dtype=x.dtype)
    hpos = 4.0 * jnp.arange(H, dtype=x.dtype)
    even = wpos[None, None, None, :] - x
    odd = hpos[None, None, :, None] - x
    is_even = (jnp.arange(G) % 2 == 0)[None, :, None, None]
    return jnp.where(is_even, even, odd)

"""Convolution ops.

Parity surface: paddle.nn.functional.conv1d/2d/3d(+_transpose)
(reference: paddle/fluid/operators/conv_op.cc, conv_cudnn_op.cu,
conv_transpose_op.cc).  The reference dispatches to cuDNN with exhaustive
algo search; on TPU a single ``lax.conv_general_dilated`` HLO maps onto the
MXU and XLA picks the tiling — there is no algo-search subsystem to port.

Layouts: paddle defaults to NCHW with OIHW kernels.  XLA:TPU internally
prefers NHWC and will transpose as needed; we pass the paddle layout through
dimension_numbers so user-facing semantics match the reference exactly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...framework.errors import InvalidArgumentError

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose", "conv3d_transpose"]


def _norm_tuple(v, n, name):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    if len(v) != n:
        raise InvalidArgumentError(f"{name} must have length {n}, got {v}")
    return v


def _padding(padding, n):
    if isinstance(padding, str):
        p = padding.upper()
        if p in ("SAME", "VALID"):
            return p
        raise InvalidArgumentError(f"unknown padding {padding!r}")
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    # paddle also allows [[0,0],[0,0],[h0,h1],[w0,w1]] incl. batch/channel
    if len(padding) == n + 2:
        return [tuple(p) for p in padding[2:]]
    return [tuple(p) for p in padding]


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n, channel_last,
             preferred_element_type=None):
    spatial = "DHW"[3 - n:]
    if channel_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    out_spec = lhs_spec
    if (x.dtype != weight.dtype
            and jnp.issubdtype(x.dtype, jnp.floating)
            and weight.dtype in (jnp.bfloat16, jnp.float16)):
        # AMP convention (paddle O1/O2 cast conv inputs): a float input
        # meeting low-precision weights computes in the weights' dtype —
        # lax.conv rejects mixed dtypes with an opaque error otherwise
        x = x.astype(weight.dtype)
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, (lhs_spec, rhs_spec, out_spec))
    # NO preferred_element_type=f32 for bf16 inputs: the TPU conv unit
    # accumulates in f32 internally regardless, and an f32-typed OUTPUT
    # breaks autodiff — the weight-gradient transpose rule feeds the f32
    # cotangent and the saved bf16 activation into one conv, which rejects
    # mixed dtypes.  bf16-in/bf16-out is the AMP storage convention.
    out = lax.conv_general_dilated(
        x, weight,
        window_strides=_norm_tuple(stride, n, "stride"),
        padding=_padding(padding, n),
        rhs_dilation=_norm_tuple(dilation, n, "dilation"),
        dimension_numbers=dn,
        feature_group_count=groups,
        # int8 path (slim.quantization Int8Conv2D) asks for an i32
        # accumulator explicitly; float paths keep the default (see above)
        preferred_element_type=preferred_element_type,
    )
    if bias is not None:
        b = jnp.asarray(bias, out.dtype)
        shape = [1] * out.ndim
        shape[-1 if channel_last else 1] = b.size
        out = out + b.reshape(shape)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_nd(jnp.asarray(x), jnp.asarray(weight), bias, stride, padding,
                    dilation, groups, 1, data_format in ("NLC",))


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """Parity: paddle.nn.functional.conv2d (ref: operators/conv_op.cc)."""
    return _conv_nd(jnp.asarray(x), jnp.asarray(weight), bias, stride, padding,
                    dilation, groups, 2, data_format == "NHWC")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(jnp.asarray(x), jnp.asarray(weight), bias, stride, padding,
                    dilation, groups, 3, data_format == "NDHWC")


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, n, channel_last, output_size):
    x = jnp.asarray(x)
    weight = jnp.asarray(weight)  # paddle transpose-conv kernel layout: (C_in, C_out//g, *k)
    if (x.dtype != weight.dtype
            and jnp.issubdtype(x.dtype, jnp.floating)
            and weight.dtype in (jnp.bfloat16, jnp.float16)):
        x = x.astype(weight.dtype)  # AMP convention, as in _conv_nd
    spatial = "DHW"[3 - n:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    # transpose_kernel=True swaps the I/O axes of the given spec and flips the
    # spatial dims, so the (C_in, C_out, *k) paddle kernel is described as
    # "OI"+spatial here (the layout a forward conv's gradient kernel has).
    rhs_spec = "OI" + spatial
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, (lhs_spec, rhs_spec, lhs_spec))
    strides = _norm_tuple(stride, n, "stride")
    dil = _norm_tuple(dilation, n, "dilation")
    pads = _padding(padding, n)
    opad = _norm_tuple(output_padding, n, "output_padding") if output_padding else (0,) * n
    if isinstance(pads, str):
        pad_cfg = pads
    else:
        # paddle transposed-conv padding p ↔ raw dilated-conv padding
        # (dk-1-p); output_padding extends the high side.
        k_dil = [dil[i] * (weight.shape[2 + i] - 1) + 1 for i in range(n)]
        pad_cfg = [(k_dil[i] - 1 - pads[i][0], k_dil[i] - 1 - pads[i][1] + opad[i])
                   for i in range(n)]
        opad = (0,) * n  # folded into pad_cfg
    if groups > 1:
        # grouped transpose conv: split along the input-channel axis of both
        xs = jnp.split(x, groups, axis=(x.ndim - 1) if channel_last else 1)
        ws = jnp.split(weight, groups, axis=0)
        outs = [
            lax.conv_transpose(xg, wg, strides=strides, padding=pad_cfg,
                               rhs_dilation=dil, dimension_numbers=dn,
                               transpose_kernel=True)
            for xg, wg in zip(xs, ws)
        ]
        out = jnp.concatenate(outs, axis=(x.ndim - 1) if channel_last else 1)
    else:
        out = lax.conv_transpose(x, weight, strides=strides, padding=pad_cfg,
                                 rhs_dilation=dil, dimension_numbers=dn,
                                 transpose_kernel=True)
    if any(p > 0 for p in opad):
        widths = [(0, 0)] * out.ndim
        for i, p in enumerate(opad):
            dim = (1 + i) if channel_last else (2 + i)
            widths[dim] = (0, p)
        out = jnp.pad(out, widths)
    if output_size is not None:
        # crop/pad to the requested spatial size
        target = tuple(output_size)
        slices = [slice(None)] * out.ndim
        start_dim = 1 if channel_last else 2
        for i, t in enumerate(target):
            slices[start_dim + i] = slice(0, t)
        out = out[tuple(slices)]
    if bias is not None:
        b = jnp.asarray(bias, out.dtype)
        shape = [1] * out.ndim
        shape[-1 if channel_last else 1] = b.size
        out = out + b.reshape(shape)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 1, data_format == "NLC", output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    """Parity: paddle.nn.functional.conv2d_transpose (ref: operators/conv_transpose_op.cc)."""
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 2, data_format == "NHWC", output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 3, data_format == "NDHWC", output_size)

"""Extension ops — row_conv, diag_embed.

Parity: python/paddle/nn/functional/extension.py (row_conv:151,
diag_embed) over operators/row_conv_op.cc and diag_embed_op.cc.  Both are
data-layout ops: row_conv is the DeepSpeech2 lookahead convolution (a
causal-in-reverse depthwise conv along time), diag_embed builds batched
diagonal matrices.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["row_conv", "diag_embed"]


def row_conv(input, weight, act=None, name=None):
    """Lookahead row convolution (ref: operators/row_conv_op.cc —
    out[t] = Σ_{j<k} x[t+j]·w[j], zero-padded at the sequence end).

    input ``[B, T, D]``, weight ``[k, D]`` (k = future_context_size + 1).
    """
    x = jnp.asarray(input)
    w = jnp.asarray(weight, x.dtype)
    k = w.shape[0]
    T = x.shape[1]
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = sum(xp[:, j:j + T, :] * w[j] for j in range(k))
    if act:
        from . import activation as A

        fn = getattr(A, act, None)
        if fn is None:
            raise ValueError(f"unsupported act {act!r}")
        out = fn(out)
    return out


def diag_embed(input, offset: int = 0, dim1: int = -2, dim2: int = -1,
               name=None):
    """Batched diagonal-matrix construction (ref: operators/diag_embed_op):
    ``out[..., i, i+offset] = input[..., i]`` with the two new axes placed
    at ``dim1``/``dim2``."""
    x = jnp.asarray(input)
    n = x.shape[-1] + abs(offset)
    rows = jnp.arange(x.shape[-1]) + max(-offset, 0)
    cols = jnp.arange(x.shape[-1]) + max(offset, 0)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    out = out.at[..., rows, cols].set(x)
    # the diagonal plane currently sits in the last two axes; move to
    # (dim1, dim2) of the OUTPUT rank
    ndim = out.ndim
    d1 = dim1 % ndim
    d2 = dim2 % ndim
    if (d1, d2) != (ndim - 2, ndim - 1):
        out = jnp.moveaxis(out, (ndim - 2, ndim - 1), (d1, d2))
    return out

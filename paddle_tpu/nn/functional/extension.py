"""Extension ops — row_conv, diag_embed, gather_tree.

Parity: python/paddle/nn/functional/extension.py (row_conv:151,
diag_embed) over operators/row_conv_op.cc and diag_embed_op.cc, plus the
beam-search backtrace op gather_tree (fluid/layers/nn.py:14972 over
operators/gather_tree_op.h:27).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["row_conv", "diag_embed", "gather_tree"]


def row_conv(input, weight, act=None, name=None):
    """Lookahead row convolution (ref: operators/row_conv_op.cc —
    out[t] = Σ_{j<k} x[t+j]·w[j], zero-padded at the sequence end).

    input ``[B, T, D]``, weight ``[k, D]`` (k = future_context_size + 1).
    """
    x = jnp.asarray(input)
    w = jnp.asarray(weight, x.dtype)
    k = w.shape[0]
    T = x.shape[1]
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = sum(xp[:, j:j + T, :] * w[j] for j in range(k))
    if act:
        from . import activation as A

        fn = getattr(A, act, None)
        if fn is None:
            raise ValueError(f"unsupported act {act!r}")
        out = fn(out)
    return out


def diag_embed(input, offset: int = 0, dim1: int = -2, dim2: int = -1,
               name=None):
    """Batched diagonal-matrix construction (ref: operators/diag_embed_op):
    ``out[..., i, i+offset] = input[..., i]`` with the two new axes placed
    at ``dim1``/``dim2``."""
    x = jnp.asarray(input)
    n = x.shape[-1] + abs(offset)
    rows = jnp.arange(x.shape[-1]) + max(-offset, 0)
    cols = jnp.arange(x.shape[-1]) + max(offset, 0)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    out = out.at[..., rows, cols].set(x)
    # the diagonal plane currently sits in the last two axes; move to
    # (dim1, dim2) of the OUTPUT rank
    ndim = out.ndim
    d1 = dim1 % ndim
    d2 = dim2 % ndim
    if (d1, d2) != (ndim - 2, ndim - 1):
        out = jnp.moveaxis(out, (ndim - 2, ndim - 1), (d1, d2))
    return out


def gather_tree(ids, parents):
    """Backtrace beam-search ancestry to full sequences (reference kernel
    operators/gather_tree_op.h:27): for each (batch, beam) start from the
    last step's own slot and follow ``parents`` backwards, reading
    ``ids`` along the path.

    ids/parents: int ``[max_time, batch, beam]`` → same-shape output.
    TPU-native: one reversed ``lax.scan`` carrying the current ancestor
    slot per (batch, beam) — no host loop, jit/grad-safe (int path).
    """
    ids = jnp.asarray(ids)
    parents = jnp.asarray(parents)
    if ids.ndim != 3:
        raise ValueError(f"gather_tree expects [time, batch, beam], "
                         f"got shape {ids.shape}")
    beam = ids.shape[2]

    def step(slot, xs):
        ids_t, parents_t = xs  # [batch, beam] each
        out_t = jnp.take_along_axis(ids_t, slot, axis=1)
        next_slot = jnp.take_along_axis(parents_t, slot, axis=1)
        return next_slot, out_t

    # last step reads its own slot; earlier steps follow the parent chain
    init = jnp.broadcast_to(jnp.arange(beam, dtype=parents.dtype),
                            ids.shape[1:])
    _, rev = jax.lax.scan(step, init, (jnp.flip(ids, 0), jnp.flip(parents, 0)))
    return jnp.flip(rev, 0)

"""Normalization ops.

Parity surface: paddle.nn.functional.{batch_norm,layer_norm,instance_norm,
group_norm,local_response_norm,normalize} (reference:
paddle/fluid/operators/batch_norm_op.cc/.cu (cuDNN), layer_norm_op.cu,
group_norm_op.cc, instance_norm_op.cc, norm_op.cc).

The reference hand-fuses these as CUDA kernels; under XLA each is a handful
of elementwise/reduce HLOs that fuse with neighbors automatically, which is
why there is no custom kernel here.  All stats accumulate in float32 even
for bf16 inputs (TPU numerics policy; matches cuDNN's float accumulation).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.errors import InvalidArgumentError

__all__ = [
    "batch_norm", "layer_norm", "instance_norm", "group_norm",
    "local_response_norm", "normalize",
]


def _stat_dtype(x):
    return jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Functional batch norm.

    Returns ``(out, new_mean, new_var)`` in training mode (functional stat
    update — the Layer wrapper assigns them back), ``out`` in eval mode.
    Paddle's momentum convention: new = momentum*old + (1-momentum)*batch.
    """
    x = jnp.asarray(x)
    ch_axis = x.ndim - 1 if data_format in ("NHWC", "NLC", "NDHWC") else 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    sd = _stat_dtype(x)
    low_precision = sd != x.dtype  # bf16/f16 activations (AMP path)

    if use_global_stats is None:
        use_global_stats = not training

    if training and not use_global_stats:
        if low_precision:
            # TPU fast path: one logical pass over the bf16 activation — two
            # reductions (sum x, sum x²) that XLA fuses into a single kernel
            # with f32 accumulators, instead of materializing a f32 copy and
            # re-reading it for jnp.var.  Measured +19% ResNet-50 train step
            # on v5e vs the two-pass f32-upcast version.
            # Numerics: E[x²]−E[x]² cancels when mean²≫var, and the folded
            # bf16 shift below rounds at |mean·inv·w| scale.  That regime is
            # already unresolvable in the INPUT: bf16 x at |mean|≫std cannot
            # represent the std in the first place (8-bit mantissa), so the
            # two-pass f32 form recovers nothing — this is the same fused
            # one-pass form TF/XLA fused batch norm uses on TPU.  f32/f64
            # inputs keep the exact two-pass path below.
            n = 1
            for i in axes:
                n *= x.shape[i]
            xf = x.astype(sd)
            mean = jnp.sum(xf, axis=axes) / n
            var = jnp.maximum(
                jnp.sum(jax.lax.square(xf), axis=axes) / n
                - jax.lax.square(mean), 0.0)
        else:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
        new_mean = momentum * jnp.asarray(running_mean, sd) + (1 - momentum) * mean
        new_var = momentum * jnp.asarray(running_var, sd) + (1 - momentum) * var
        # running stats keep their declared dtype: a functional update must
        # not change the carry's dtype (lax.scan carries, recompile avoidance)
        new_mean = new_mean.astype(jnp.asarray(running_mean).dtype)
        new_var = new_var.astype(jnp.asarray(running_var).dtype)
    else:
        mean = jnp.asarray(running_mean, sd)
        var = jnp.asarray(running_var, sd)
        new_mean, new_var = None, None

    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    inv = jax.lax.rsqrt(var + epsilon)
    if low_precision:
        # fold (x−mean)·inv·w + b into one bf16 FMA: x·scale + shift, with
        # scale/shift computed per-channel in f32 then cast once
        scale = inv if weight is None else inv * jnp.asarray(weight, sd)
        shift = -mean * scale
        if bias is not None:
            shift = shift + jnp.asarray(bias, sd)
        out = (x * scale.astype(x.dtype).reshape(shape)
               + shift.astype(x.dtype).reshape(shape))
    else:
        out = (x - mean.reshape(shape)) * inv.reshape(shape)
        if weight is not None:
            out = out * jnp.asarray(weight, sd).reshape(shape)
        if bias is not None:
            out = out + jnp.asarray(bias, sd).reshape(shape)
        out = out.astype(x.dtype)
    if new_mean is not None:
        return out, new_mean, new_var
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    """Parity: paddle.nn.functional.layer_norm (ref: operators/layer_norm_op.cu)."""
    x = jnp.asarray(x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    normalized_shape = tuple(normalized_shape)
    n = len(normalized_shape)
    if tuple(x.shape[x.ndim - n:]) != normalized_shape:
        raise InvalidArgumentError(
            f"normalized_shape {normalized_shape} does not match trailing dims of {x.shape}")
    axes = tuple(range(x.ndim - n, x.ndim))
    sd = _stat_dtype(x)
    xf = x.astype(sd)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * jnp.asarray(weight, sd)
    if bias is not None:
        out = out + jnp.asarray(bias, sd)
    return out.astype(x.dtype)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    x = jnp.asarray(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ch_axis = x.ndim - 1 if channel_last else 1
    axes = tuple(i for i in range(2, x.ndim)) if not channel_last else tuple(i for i in range(1, x.ndim - 1))
    sd = _stat_dtype(x)
    xf = x.astype(sd)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        shape = [1] * x.ndim
        shape[ch_axis] = x.shape[ch_axis]
        out = out * jnp.asarray(weight, sd).reshape(shape)
    if bias is not None:
        shape = [1] * x.ndim
        shape[ch_axis] = x.shape[ch_axis]
        out = out + jnp.asarray(bias, sd).reshape(shape)
    return out.astype(x.dtype)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = jnp.asarray(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ch_axis = x.ndim - 1 if channel_last else 1
    C = x.shape[ch_axis]
    if C % num_groups != 0:
        raise InvalidArgumentError(f"channels {C} not divisible by groups {num_groups}")
    sd = _stat_dtype(x)
    xf = x.astype(sd)
    if channel_last:
        moved = jnp.moveaxis(xf, ch_axis, 1)
    else:
        moved = xf
    N = moved.shape[0]
    grouped = moved.reshape((N, num_groups, C // num_groups) + moved.shape[2:])
    axes = tuple(range(2, grouped.ndim))
    mean = jnp.mean(grouped, axis=axes, keepdims=True)
    var = jnp.var(grouped, axis=axes, keepdims=True)
    out = ((grouped - mean) * jax.lax.rsqrt(var + epsilon)).reshape(moved.shape)
    if channel_last:
        out = jnp.moveaxis(out, 1, ch_axis)
    shape = [1] * x.ndim
    shape[ch_axis] = C
    if weight is not None:
        out = out * jnp.asarray(weight, sd).reshape(shape)
    if bias is not None:
        out = out + jnp.asarray(bias, sd).reshape(shape)
    return out.astype(x.dtype)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    """Parity: paddle.nn.functional.local_response_norm (ref: operators/lrn_op.cc)."""
    x = jnp.asarray(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ch_axis = x.ndim - 1 if channel_last else 1
    sq = jnp.square(x.astype(jnp.float32))
    # sum over a window of `size` channels centered at each channel
    pad_lo = (size - 1) // 2
    pad_hi = size - 1 - pad_lo
    widths = [(0, 0)] * x.ndim
    widths[ch_axis] = (pad_lo, pad_hi)
    padded = jnp.pad(sq, widths)
    window = [1] * x.ndim
    window[ch_axis] = size
    summed = jax.lax.reduce_window(padded, jnp.array(0, jnp.float32), jax.lax.add,
                                   tuple(window), (1,) * x.ndim, "VALID")
    div = jnp.power(k + alpha * summed, beta)
    return (x.astype(jnp.float32) / div).astype(x.dtype)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = jnp.asarray(x)
    if p == 2:
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    else:
        n = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(n, epsilon)

"""Pooling ops.

Parity surface: paddle.nn.functional pooling (reference:
paddle/fluid/operators/pool_op.cc, pool_cudnn_op.cu, operators/math/pooling.cu).
On TPU pooling is a ``lax.reduce_window`` HLO.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ...framework.errors import InvalidArgumentError

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d",
    "max_pool1d", "max_pool2d", "max_pool3d",
    "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d",
]


def _norm(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    return v * n if len(v) == 1 else v


def _pool(x, kernel, stride, padding, n, channel_last, reducer, init, ceil_mode):
    x = jnp.asarray(x)
    kernel = _norm(kernel, n)
    stride = _norm(stride if stride is not None else kernel, n)
    if isinstance(padding, str):
        raise InvalidArgumentError("string padding not supported for pool; use ints")
    padding = _norm(padding, n)

    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = [(0, 0)] + [(p, p) for p in padding] + [(0, 0)]
        spatial_dims = list(range(1, 1 + n))
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = [(0, 0), (0, 0)] + [(p, p) for p in padding]
        spatial_dims = list(range(2, 2 + n))

    if ceil_mode:
        # add extra right-padding so ceil-division windows fit
        for i, d in enumerate(spatial_dims):
            size = x.shape[d] + 2 * padding[i]
            rem = (size - kernel[i]) % stride[i]
            if rem != 0:
                lo, hi = pads[d]
                pads[d] = (lo, hi + (stride[i] - rem))
    return lax.reduce_window(x, init, reducer, window, strides, pads), kernel, pads, spatial_dims


def _avg_pool(x, kernel, stride, padding, n, channel_last, exclusive, ceil_mode):
    x = jnp.asarray(x)
    # init must be a CONCRETE numpy scalar: under jit tracing a jnp.array
    # init defeats lax.reduce_window's monoid detection, lowering to the
    # generic reduce_window primitive which has no autodiff rule
    summed, kernel_t, pads, spatial_dims = _pool(
        x, kernel, stride, padding, n, channel_last, lax.add,
        np.array(0, x.dtype), ceil_mode)
    if exclusive:
        # divide by the count of valid (non-pad) elements per window
        ones = jnp.ones([x.shape[d] for d in spatial_dims], x.dtype)
        shape = [1] * x.ndim
        for d in spatial_dims:
            shape[d] = x.shape[d]
        ones = ones.reshape(shape)
        stride_t = _norm(stride if stride is not None else kernel, n)
        if channel_last:
            window = (1,) + _norm(kernel, n) + (1,)
            strides = (1,) + stride_t + (1,)
        else:
            window = (1, 1) + _norm(kernel, n)
            strides = (1, 1) + stride_t
        counts = lax.reduce_window(jnp.broadcast_to(ones, shape),
                                   np.array(0, x.dtype),
                                   lax.add, window, strides, pads)
        return summed / counts
    return summed / np.prod(kernel_t)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False,
               data_format="NCL", name=None):
    return _avg_pool(x, kernel_size, stride, padding, 1, data_format == "NLC", exclusive, ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    if divisor_override:
        x = jnp.asarray(x)
        summed, kernel_t, _, _ = _pool(x, kernel_size, stride, padding, 2,
                                       data_format == "NHWC", lax.add,
                                       np.array(0, x.dtype), ceil_mode)
        return summed / divisor_override
    return _avg_pool(x, kernel_size, stride, padding, 2, data_format == "NHWC", exclusive, ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    if divisor_override:
        x = jnp.asarray(x)
        summed, _, _, _ = _pool(x, kernel_size, stride, padding, 3,
                                data_format == "NDHWC", lax.add,
                                np.array(0, x.dtype), ceil_mode)
        return summed / divisor_override
    return _avg_pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC", exclusive, ceil_mode)


def _max_pool(x, kernel, stride, padding, n, channel_last, ceil_mode):
    x = jnp.asarray(x)
    neg_inf = np.array(
        -np.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else np.iinfo(np.dtype(x.dtype)).min, x.dtype)
    out, _, _, _ = _pool(x, kernel, stride, padding, n, channel_last, lax.max, neg_inf, ceil_mode)
    return out


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCL", name=None):
    out = _max_pool(x, kernel_size, stride, padding, 1, data_format == "NLC", ceil_mode)
    return (out, _max_pool_indices(x, kernel_size, stride, padding, 1, data_format == "NLC", ceil_mode)) if return_mask else out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCHW", name=None):
    out = _max_pool(x, kernel_size, stride, padding, 2, data_format == "NHWC", ceil_mode)
    return (out, _max_pool_indices(x, kernel_size, stride, padding, 2, data_format == "NHWC", ceil_mode)) if return_mask else out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCDHW", name=None):
    out = _max_pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC", ceil_mode)
    return (out, _max_pool_indices(x, kernel_size, stride, padding, 3, data_format == "NDHWC", ceil_mode)) if return_mask else out


def _max_pool_indices(x, kernel, stride, padding, n, channel_last, ceil_mode=False):
    """Flat spatial argmax indices (paddle return_mask parity). Computed by
    an extra reduce_window over (value, iota) pairs — only built when
    requested; uses the same window/stride/pad (incl. ceil_mode) as the
    value pool so shapes always match."""
    x = jnp.asarray(x)
    spatial_shape = x.shape[1:-1] if channel_last else x.shape[2:]
    size = int(np.prod(spatial_shape))
    iota = jnp.arange(size, dtype=jnp.int64).reshape(spatial_shape)
    shape = [1] * x.ndim
    for i, d in enumerate(range(1, 1 + n) if channel_last else range(2, 2 + n)):
        shape[d] = spatial_shape[i]
    iota = jnp.broadcast_to(iota.reshape(shape), x.shape)

    kernel_t = _norm(kernel, n)
    stride_t = _norm(stride if stride is not None else kernel, n)
    padding_t = _norm(padding, n)
    if channel_last:
        window = (1,) + kernel_t + (1,)
        strides = (1,) + stride_t + (1,)
        pads = [(0, 0)] + [(p, p) for p in padding_t] + [(0, 0)]
        spatial_dims = list(range(1, 1 + n))
    else:
        window = (1, 1) + kernel_t
        strides = (1, 1) + stride_t
        pads = [(0, 0), (0, 0)] + [(p, p) for p in padding_t]
        spatial_dims = list(range(2, 2 + n))
    if ceil_mode:
        for i, dd in enumerate(spatial_dims):
            sz = x.shape[dd] + 2 * padding_t[i]
            rem = (sz - kernel_t[i]) % stride_t[i]
            if rem != 0:
                lo, hi = pads[dd]
                pads[dd] = (lo, hi + (stride_t[i] - rem))

    neg_inf = jnp.array(-jnp.inf, x.dtype)

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        pick_b = bv > av
        return lax.select(pick_b, bv, av), lax.select(pick_b, bi, ai)

    _, idx = lax.reduce_window((x, iota), (neg_inf, jnp.array(-1, jnp.int64)),
                               reducer, window, strides, pads)
    return idx


def _adaptive_pool(x, output_size, n, channel_last, op):
    x = jnp.asarray(x)
    if isinstance(output_size, int):
        output_size = (output_size,) * n
    output_size = tuple(s if s is not None else x.shape[(1 + i) if channel_last else (2 + i)]
                        for i, s in enumerate(output_size))
    spatial_start = 1 if channel_last else 2
    out = x
    for i in range(n):
        dim = spatial_start + i
        in_size = out.shape[dim]
        o = output_size[i]
        if in_size % o == 0:
            # even split: reshape + reduce (fast path)
            k = in_size // o
            new_shape = out.shape[:dim] + (o, k) + out.shape[dim + 1:]
            r = out.reshape(new_shape)
            out = jnp.max(r, axis=dim + 1) if op == "max" else jnp.mean(r, axis=dim + 1)
        else:
            # uneven: gather per output index (adaptive windows)
            starts = np.floor(np.arange(o) * in_size / o).astype(np.int64)
            ends = np.ceil((np.arange(o) + 1) * in_size / o).astype(np.int64)
            pieces = []
            for s, e in zip(starts, ends):
                sl = [slice(None)] * out.ndim
                sl[dim] = slice(int(s), int(e))
                seg = out[tuple(sl)]
                red = jnp.max(seg, axis=dim, keepdims=True) if op == "max" else jnp.mean(seg, axis=dim, keepdims=True)
                pieces.append(red)
            out = jnp.concatenate(pieces, axis=dim)
    return out


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, False, "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format == "NHWC", "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format == "NDHWC", "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 1, False, "max")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 2, False, "max")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 3, False, "max")
    return (out, None) if return_mask else out

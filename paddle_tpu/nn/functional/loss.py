"""Loss functions.

Parity surface: paddle.nn.functional losses (reference:
paddle/fluid/operators/cross_entropy_op.cc, softmax_with_cross_entropy_op.cu,
bce_loss_op.cc, smooth_l1_loss_op.cc, kldiv_loss_op.cc, nll_loss_op.cc,
margin_rank_loss_op.cc, ...; python/paddle/nn/functional/loss.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import dtype as _dt
from ...framework.errors import InvalidArgumentError

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "kl_div", "smooth_l1_loss", "margin_ranking_loss", "cosine_embedding_loss",
    "hinge_embedding_loss", "triplet_margin_loss", "label_smooth",
    "square_error_cost", "log_loss", "sigmoid_focal_loss", "dice_loss",
    "npair_loss", "cosine_similarity", "ctc_loss", "hsigmoid_loss",
]


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    if reduction == "none":
        return loss
    raise InvalidArgumentError(f"unknown reduction {reduction!r}")


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """Parity: paddle.nn.functional.cross_entropy
    (ref: operators/softmax_with_cross_entropy_op.cu — fused on GPU; XLA
    fuses the log_softmax+gather chain the same way)."""
    input = jnp.asarray(input)
    label = jnp.asarray(label)
    logp = jax.nn.log_softmax(input, axis=axis) if use_softmax else jnp.log(jnp.clip(input, 1e-15, None))

    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis)
    else:
        lbl = label
        if lbl.ndim == input.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis)
        n_classes = input.shape[axis]
        if label_smoothing > 0.0:
            onehot = jax.nn.one_hot(lbl, n_classes, dtype=logp.dtype, axis=axis)
            smoothed = onehot * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(smoothed * logp, axis=axis)
        else:
            safe = jnp.clip(lbl, 0, n_classes - 1)
            picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, axis).astype(jnp.int32), axis=axis)
            loss = -jnp.squeeze(picked, axis)
        w = None
        if weight is not None:
            w = jnp.take(jnp.asarray(weight, logp.dtype), jnp.clip(lbl, 0, n_classes - 1))
            loss = loss * w
        mask = (lbl != ignore_index)
        loss = jnp.where(mask, loss, 0.0)
        if reduction == "mean":
            if w is not None:
                denom = jnp.sum(jnp.where(mask, w, 0.0))
            else:
                denom = jnp.maximum(jnp.sum(mask.astype(logp.dtype)), 1.0)
            return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = jnp.expand_dims(loss, axis)
    if return_softmax:
        return loss, jax.nn.softmax(jnp.asarray(logits), axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    input = jnp.clip(jnp.asarray(input), 1e-12, 1.0 - 1e-7)
    label = jnp.asarray(label, input.dtype)
    loss = -(label * jnp.log(input) + (1 - label) * jnp.log1p(-input))
    if weight is not None:
        loss = loss * jnp.asarray(weight, input.dtype)
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    logit = jnp.asarray(logit)
    label = jnp.asarray(label, logit.dtype)
    # numerically stable: max(x,0) - x*y + log(1+exp(-|x|))
    neg_abs = -jnp.abs(logit)
    loss = jnp.maximum(logit, 0.0) - logit * label + jnp.log1p(jnp.exp(neg_abs))
    if pos_weight is not None:
        pw = jnp.asarray(pos_weight, logit.dtype)
        log_sig = jax.nn.log_sigmoid(logit)
        log_sig_neg = jax.nn.log_sigmoid(-logit)
        loss = -(pw * label * log_sig + (1 - label) * log_sig_neg)
    if weight is not None:
        loss = loss * jnp.asarray(weight, logit.dtype)
    return _reduce(loss, reduction)


def mse_loss(input, label, reduction="mean", name=None):
    d = jnp.asarray(input) - jnp.asarray(label)
    return _reduce(jnp.square(d), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    d = jnp.abs(jnp.asarray(input) - jnp.asarray(label))
    return _reduce(d, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    input = jnp.asarray(input)  # log-probabilities (N, C, ...)
    label = jnp.asarray(label)
    n_classes = input.shape[1]
    safe = jnp.clip(label, 0, n_classes - 1)
    picked = jnp.take_along_axis(input, jnp.expand_dims(safe, 1).astype(jnp.int32), axis=1)
    loss = -jnp.squeeze(picked, 1)
    if weight is not None:
        w = jnp.take(jnp.asarray(weight, input.dtype), safe)
        loss = loss * w
    mask = label != ignore_index
    loss = jnp.where(mask, loss, 0.0)
    if reduction == "mean":
        if weight is not None:
            denom = jnp.sum(jnp.where(mask, jnp.take(jnp.asarray(weight, input.dtype), safe), 0.0))
        else:
            denom = jnp.maximum(jnp.sum(mask.astype(input.dtype)), 1.0)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", name=None):
    """input = log-probs, label = probs (paddle semantics)."""
    input = jnp.asarray(input)
    label = jnp.asarray(label, input.dtype)
    loss = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    d = jnp.asarray(input) - jnp.asarray(label)
    ad = jnp.abs(d)
    loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta) * delta
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    loss = jnp.maximum(0.0, -jnp.asarray(label) * (jnp.asarray(input) - jnp.asarray(other)) + margin)
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    cos = cosine_similarity(input1, input2, axis=-1)
    label = jnp.asarray(label)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    input = jnp.asarray(input)
    label = jnp.asarray(label, input.dtype)
    loss = jnp.where(label == 1, input, jnp.maximum(0.0, margin - input))
    return _reduce(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def pdist(a, b):
        return jnp.sum(jnp.abs(a - b + epsilon) ** p, axis=-1) ** (1.0 / p)

    input, positive, negative = map(jnp.asarray, (input, positive, negative))
    dp = pdist(input, positive)
    dn = pdist(input, negative)
    if swap:
        dn = jnp.minimum(dn, pdist(positive, negative))
    return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    """Parity: paddle.nn.functional.label_smooth (ref: operators/label_smooth_op.cc)."""
    label = jnp.asarray(label)
    n = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * jnp.asarray(prior_dist, label.dtype)
    return (1 - epsilon) * label + epsilon / n


def square_error_cost(input, label):
    """Legacy fluid.layers.square_error_cost parity."""
    d = jnp.asarray(input) - jnp.asarray(label)
    return jnp.square(d)


def log_loss(input, label, epsilon=1e-4, name=None):
    input = jnp.asarray(input)
    label = jnp.asarray(label, input.dtype)
    return -label * jnp.log(input + epsilon) - (1 - label) * jnp.log(1 - input + epsilon)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    logit = jnp.asarray(logit)
    label = jnp.asarray(label, logit.dtype)
    p = jax.nn.sigmoid(logit)
    ce = binary_cross_entropy_with_logits(logit, label, reduction="none")
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * ((1 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / jnp.asarray(normalizer, logit.dtype)
    return _reduce(loss, reduction)


def dice_loss(input, label, epsilon=1e-5, name=None):
    input = jnp.asarray(input)
    label = jnp.asarray(label)
    label_oh = jax.nn.one_hot(jnp.squeeze(label, -1), input.shape[-1], dtype=input.dtype)
    reduce_axes = tuple(range(1, input.ndim))
    inter = jnp.sum(input * label_oh, axis=reduce_axes)
    denom = jnp.sum(input, axis=reduce_axes) + jnp.sum(label_oh, axis=reduce_axes)
    return jnp.mean(1 - (2 * inter + epsilon) / (denom + epsilon))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    anchor = jnp.asarray(anchor)
    positive = jnp.asarray(positive)
    labels = jnp.asarray(labels)
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), 1)) +
                    jnp.mean(jnp.sum(jnp.square(positive), 1))) * 0.25
    sim = anchor @ positive.T
    lbl = (labels[:, None] == labels[None, :]).astype(anchor.dtype)
    lbl = lbl / jnp.sum(lbl, axis=1, keepdims=True)
    xent = jnp.mean(-jnp.sum(lbl * jax.nn.log_softmax(sim, axis=1), axis=1))
    return xent + reg


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    x1 = jnp.asarray(x1)
    x2 = jnp.asarray(x2, x1.dtype)
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(jnp.square(x1), axis=axis))
    n2 = jnp.sqrt(jnp.sum(jnp.square(x2), axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC (ref: operators/warpctc_op.cc wraps warp-ctc; here: pure-XLA
    forward algorithm in log space via lax.scan — jit/grad-able)."""
    log_probs = jnp.asarray(log_probs)  # (T, N, C) paddle layout
    labels = jnp.asarray(labels)  # (N, S)
    T, N, C = log_probs.shape
    S = labels.shape[1]
    neg_inf = jnp.array(-1e30, log_probs.dtype)

    # extended label sequence with blanks: length 2S+1
    ext = jnp.full((N, 2 * S + 1), blank, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    ext_len = 2 * label_lengths + 1

    def logsumexp2(a, b):
        m = jnp.maximum(a, b)
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        return jnp.where(jnp.isfinite(m), m_safe + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe)), neg_inf)

    # alpha init
    alpha0 = jnp.full((N, 2 * S + 1), neg_inf)
    p0 = log_probs[0]  # (N, C)
    alpha0 = alpha0.at[:, 0].set(jnp.take_along_axis(p0, ext[:, :1], axis=1)[:, 0])
    if 2 * S + 1 > 1:
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(ext_len > 1, jnp.take_along_axis(p0, ext[:, 1:2], axis=1)[:, 0], neg_inf))

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, logp_t):
        shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(same_as_prev2, neg_inf, shift2)
        merged = logsumexp2(logsumexp2(alpha, shift1), shift2)
        emit = jnp.take_along_axis(logp_t, ext, axis=1)
        return merged + emit, None

    def scan_body(carry, t):
        alpha = carry
        new_alpha, _ = step(alpha, log_probs[t])
        # freeze once past this sequence's input length
        new_alpha = jnp.where((t < input_lengths)[:, None], new_alpha, alpha)
        return new_alpha, None

    alpha, _ = jax.lax.scan(scan_body, alpha0, jnp.arange(1, T))
    idx_last = (ext_len - 1)[:, None]
    idx_prev = jnp.maximum(ext_len - 2, 0)[:, None]
    ll = logsumexp2(
        jnp.take_along_axis(alpha, idx_last, axis=1)[:, 0],
        jnp.take_along_axis(alpha, idx_prev, axis=1)[:, 0],
    )
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.asarray(input_lengths, loss.dtype)
    if reduction == "mean":
        # paddle semantics: per-sample NLL / label_length, then batch mean
        return jnp.mean(loss / jnp.maximum(jnp.asarray(label_lengths, loss.dtype), 1.0))
    return _reduce(loss, reduction)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss over a complete binary class tree
    (ref: nn/functional/loss.py:335 over operators/hierarchical_sigmoid_op
    + math/matrix_bit_code.h SimpleCode).

    Default tree: leaf code ``c = label + num_classes``; walking bits from
    the LSB, the (bit+1)-level parent node is ``(c >> (bit+1)) - 1`` and
    the binary target is ``(c >> bit) & 1`` (matrix_bit_code.h:119-121).
    Each path node is a binary logistic classifier; the loss sums their
    BCEs.  Custom trees pass ``path_table``/``path_code`` ``[N, L]`` (−1
    padded).  ``is_sparse`` selected a SelectedRows gradient in the
    reference; XLA's scatter-add gather gradient covers it — accepted and
    ignored.

    input ``[N, D]``, label ``[N]`` int, weight ``[num_classes-1, D]``,
    bias ``[num_classes-1]`` — returns ``[N, 1]``.
    """
    x = jnp.asarray(input)
    y = jnp.asarray(label, jnp.int32).reshape(-1)
    w = jnp.asarray(weight)
    if (path_table is None) != (path_code is None):
        raise InvalidArgumentError(
            "path_table and path_code must be given together")
    if path_table is not None:
        idx = jnp.asarray(path_table, jnp.int32)          # [N, L]
        bits = jnp.asarray(path_code, x.dtype)            # [N, L]
        valid = idx >= 0
        idx = jnp.where(valid, idx, 0)
    else:
        import math as _math

        c = y.astype(jnp.int64) + jnp.int64(num_classes)  # [N]
        L = int(_math.ceil(_math.log2(max(num_classes, 2)))) + 1
        j = jnp.arange(L, dtype=jnp.int64)[None, :]       # [1, L]
        # get_length = FindLastSet(c) - 1, in exact INTEGER arithmetic:
        # floor(log2 c) = #{k >= 1 : 2^k <= c} (a float32 log2 rounds
        # wrong near powers of two once num_classes is large — the very
        # regime hierarchical softmax exists for)
        length = jnp.sum(
            c[:, None] >= (jnp.int64(1) << jnp.arange(1, L + 1,
                                                      dtype=jnp.int64))[None],
            axis=1, dtype=jnp.int64)[:, None]
        valid = j < length
        idx = jnp.where(valid, (c[:, None] >> (j + 1)) - 1, 0)
        bits = ((c[:, None] >> j) & 1).astype(x.dtype)
    w_path = jnp.take(w, idx, axis=0)                     # [N, L, D]
    logits = jnp.einsum("nld,nd->nl", w_path.astype(x.dtype), x)
    if bias is not None:
        logits = logits + jnp.take(
            jnp.asarray(bias, x.dtype).reshape(-1), idx, axis=0)
    per_node = binary_cross_entropy_with_logits(logits, bits,
                                                reduction="none")
    per_node = jnp.where(valid, per_node, 0.0)
    return per_node.sum(axis=1, keepdims=True)

"""Deformable convolution v1/v2 (DCN).

Parity: fluid/layers/nn.py:14229 deformable_conv over
operators/deformable_conv_op.* (modulated_deformable_im2col): sampling
points are the regular conv taps displaced by learned per-position
offsets, values fetched by bilinear interpolation with zero padding
outside the map, optionally scaled by a learned modulation mask (v2).

TPU-native design: the im2col + GEMM structure is kept — the "columns"
are built with one vectorized bilinear gather (per-corner validity
masks reproduce the kernel's partial-corner boundary handling), then a
single einsum contracts kernel taps and input channels on the MXU.

Offset layout matches the reference kernel: ``[N, 2·dg·K, Ho, Wo]``
with (h, w) interleaved per tap; mask ``[N, dg·K, Ho, Wo]``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.errors import InvalidArgumentError

__all__ = ["deform_conv2d"]


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _bilinear_zeropad(img, y, x):
    """img [C, H, W]; y/x [C, ...] per-channel sample grids → values with
    zero contribution from out-of-map corners (dmcn_im2col_bilinear)."""
    C, H, W = img.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    ly = (y - y0).astype(img.dtype)
    lx = (x - x0).astype(img.dtype)
    flat = img.reshape(C, H * W)
    out = jnp.zeros(y.shape, img.dtype)
    for dy, dx, wgt in ((0, 0, (1 - ly) * (1 - lx)),
                        (0, 1, (1 - ly) * lx),
                        (1, 0, ly * (1 - lx)),
                        (1, 1, ly * lx)):
        yc = y0 + dy
        xc = x0 + dx
        ok = (yc >= 0) & (yc < H) & (xc >= 0) & (xc < W)
        idx = (jnp.clip(yc, 0, H - 1) * W
               + jnp.clip(xc, 0, W - 1)).astype(jnp.int32)
        vals = jnp.take_along_axis(flat, idx.reshape(C, -1),
                                   axis=1).reshape(y.shape)
        out = out + jnp.where(ok, vals * wgt, 0.0)
    return out


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """x ``[N, Cin, H, W]``, offset ``[N, 2·dg·K, Ho, Wo]``, weight
    ``[Cout, Cin/groups, kh, kw]``, mask ``[N, dg·K, Ho, Wo]`` (None →
    DCNv1) → ``[N, Cout, Ho, Wo]``."""
    x = jnp.asarray(x)
    offset = jnp.asarray(offset, x.dtype)
    weight = jnp.asarray(weight, x.dtype)
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    N, Cin, H, W = x.shape
    Cout, Cin_g, kh, kw = weight.shape
    K = kh * kw
    dg = int(deformable_groups)
    if Cin % dg or Cin_g * groups != Cin:
        raise InvalidArgumentError(
            f"channel split mismatch: Cin={Cin}, groups={groups}, "
            f"weight Cin/groups={Cin_g}, deformable_groups={dg}")
    Ho, Wo = offset.shape[2], offset.shape[3]
    if offset.shape[1] != 2 * dg * K:
        raise InvalidArgumentError(
            f"offset channels {offset.shape[1]} != 2·dg·K = {2 * dg * K}")
    want_ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    want_wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    if (Ho, Wo) != (want_ho, want_wo):
        raise InvalidArgumentError(
            f"offset spatial dims {(Ho, Wo)} don't match the conv output "
            f"{(want_ho, want_wo)} for input {(H, W)}, kernel "
            f"{(kh, kw)}, stride {(sh, sw)}, padding {(ph, pw)}, "
            f"dilation {(dh, dw)} — the offset head must run at the "
            f"output resolution")

    # regular tap positions: [K] each for h and w, plus output grid
    ki, kj = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
    base_h = (jnp.arange(Ho) * sh - ph)[:, None, None] \
        + (ki.reshape(-1) * dh)[None, None, :]          # [Ho, 1, K]
    base_w = (jnp.arange(Wo) * sw - pw)[:, None, None] \
        + (kj.reshape(-1) * dw)[None, None, :]          # [Wo, 1, K]
    base_h = jnp.transpose(base_h, (2, 0, 1))           # [K, Ho, 1]
    base_w = jnp.transpose(base_w, (2, 1, 0))           # [K, 1, Wo]

    off = offset.reshape(N, dg, K, 2, Ho, Wo)
    off_h = off[:, :, :, 0]
    off_w = off[:, :, :, 1]
    samp_h = base_h[None, None] + off_h                 # [N, dg, K, Ho, Wo]
    samp_w = base_w[None, None] + off_w
    rep = Cin // dg

    def per_image(img, yh, xw, m):
        # expand per-dg coords to per-channel
        yc = jnp.repeat(yh, rep, axis=0)                # [Cin, K, Ho, Wo]
        xc = jnp.repeat(xw, rep, axis=0)
        cols = _bilinear_zeropad(img, yc, xc)           # [Cin, K, Ho, Wo]
        if m is not None:
            cols = cols * jnp.repeat(m, rep, axis=0)
        return cols

    mk = (jnp.asarray(mask, x.dtype).reshape(N, dg, K, Ho, Wo)
          if mask is not None else None)  # None is an empty pytree — vmap ok
    cols = jax.vmap(per_image)(x, samp_h, samp_w, mk)
    # cols [N, Cin, K, Ho, Wo] × weight [Cout, Cin/g, K]
    wf = weight.reshape(Cout, Cin_g, K)
    if groups == 1:
        out = jnp.einsum("nckhw,ock->nohw", cols, wf)
    else:
        cols_g = cols.reshape(N, groups, Cin_g, K, Ho, Wo)
        wf_g = wf.reshape(groups, Cout // groups, Cin_g, K)
        out = jnp.einsum("ngckhw,gock->ngohw", cols_g, wf_g)
        out = out.reshape(N, Cout, Ho, Wo)
    if bias is not None:
        out = out + jnp.asarray(bias, x.dtype).reshape(1, -1, 1, 1)
    return out

"""Common NN ops: linear, dropout, embedding, interpolate, unfold, etc.

Parity surface: paddle.nn.functional common ops (reference:
operators/dropout_op.cu, lookup_table_v2_op.cu (embedding),
interpolate_op.cc, unfold_op.cc, pixel_shuffle_op.cc, mul_op/fc).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework import dtype as _dt
from ...framework.errors import InvalidArgumentError
from ...framework.random import split_key
from ..layer_base import current_rng_key

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "embedding", "one_hot", "interpolate", "upsample", "pixel_shuffle",
    "pixel_unshuffle", "channel_shuffle", "unfold", "fold", "pad",
    "sequence_mask", "bilinear", "affine_grid", "grid_sample",
    "temporal_shift", "npu_identity",
]

# re-export pad from tensor.manipulation (same op)
from ...tensor.manipulation import pad  # noqa: F401,E402


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle weight layout (in_features, out_features).

    On TPU this is a single MXU dot; bf16 inputs accumulate f32
    (ref: operators/mul_op.cc + math/blas.h → here one dot_general).
    """
    x = jnp.asarray(x)
    w = jnp.asarray(weight)
    pref = jnp.float32 if x.dtype == jnp.bfloat16 else None
    out = jnp.matmul(x, w, preferred_element_type=pref)
    if out.dtype != x.dtype:
        out = out.astype(x.dtype)
    if bias is not None:
        out = out + jnp.asarray(bias, out.dtype)
    return out


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None, key=None):
    """Parity: paddle.nn.functional.dropout (ref: operators/dropout_op.cu).

    mode='upscale_in_train' (default): scale by 1/(1-p) in training.
    mode='downscale_in_infer': scale by (1-p) at inference.
    """
    x = jnp.asarray(x)
    if p == 0.0 or not training:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    if p == 1.0:
        return jnp.zeros_like(x)
    k = key if key is not None else current_rng_key()
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        mask_shape = tuple(x.shape[i] if i in axes else 1 for i in range(x.ndim))
    else:
        mask_shape = x.shape
    # f32 keep-probability: under the x64 API surface a Python-float p
    # promotes the uniform draw to f64, which TPUs emulate at huge cost
    keep = jax.random.bernoulli(k, np.float32(1.0 - p), mask_shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return jnp.where(keep, x, 0.0).astype(x.dtype)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None, key=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training, key=key)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None, key=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training, key=key)


def alpha_dropout(x, p=0.5, training=True, name=None, key=None):
    """SELU-compatible dropout (keeps mean/variance)."""
    x = jnp.asarray(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    k = key if key is not None else current_rng_key()
    keep = jax.random.bernoulli(k, 1.0 - p, x.shape)
    a = (1.0 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
    b = -a * alpha_p * p
    return (a * jnp.where(keep, x, alpha_p) + b).astype(x.dtype)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Parity: paddle.nn.functional.embedding (ref: operators/lookup_table_v2_op.cu).

    ``sparse`` selected a SelectedRows gradient in the reference; XLA handles
    the scatter-add gradient of gather natively, so the flag is accepted and
    ignored.
    """
    x = jnp.asarray(x)
    w = jnp.asarray(weight)
    out = jnp.take(w, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None]
        out = jnp.where(mask, out, 0.0)
    return out


def one_hot(x, num_classes, name=None):
    return jax.nn.one_hot(jnp.asarray(x), num_classes, dtype=_dt.get_default_dtype())


def _resize_nearest(x, out_hw, channel_last, align_corners):
    # x: (N, C, *spatial) or (N, *spatial, C)
    spatial_start = 1 if channel_last else 2
    n_sp = len(out_hw)
    idxs = []
    for i in range(n_sp):
        in_size = x.shape[spatial_start + i]
        out_size = out_hw[i]
        scale = in_size / out_size
        idx = jnp.floor(jnp.arange(out_size) * scale).astype(jnp.int32)
        idx = jnp.clip(idx, 0, in_size - 1)
        idxs.append(idx)
    out = x
    for i, idx in enumerate(idxs):
        out = jnp.take(out, idx, axis=spatial_start + i)
    return out


def _resize_linear_1d(x, out_size, axis, align_corners, align_mode):
    in_size = x.shape[axis]
    if align_corners:
        pos = jnp.linspace(0.0, in_size - 1.0, out_size)
    else:
        if align_mode == 1:
            pos = jnp.arange(out_size) * (in_size / out_size)
        else:
            pos = (jnp.arange(out_size) + 0.5) * (in_size / out_size) - 0.5
    pos = jnp.clip(pos, 0.0, in_size - 1.0)
    lo = jnp.floor(pos).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, in_size - 1)
    w_hi = (pos - lo).astype(x.dtype)
    x_lo = jnp.take(x, lo, axis=axis)
    x_hi = jnp.take(x, hi, axis=axis)
    shape = [1] * x.ndim
    shape[axis] = out_size
    w_hi = w_hi.reshape(shape)
    return x_lo * (1 - w_hi) + x_hi * w_hi


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format=None, name=None):
    """Parity: paddle.nn.functional.interpolate (ref: operators/interpolate_op.cc)."""
    x = jnp.asarray(x)
    n_sp = x.ndim - 2
    if data_format is None:
        data_format = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[n_sp]
    channel_last = data_format in ("NWC", "NHWC", "NDHWC")
    spatial_start = 1 if channel_last else 2
    in_sizes = [x.shape[spatial_start + i] for i in range(n_sp)]
    if size is not None:
        if isinstance(size, (list, tuple)):
            out_sizes = [int(s) for s in size]
        else:
            out_sizes = [int(size)] * n_sp
    elif scale_factor is not None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * n_sp
        out_sizes = [int(np.floor(i * s)) for i, s in zip(in_sizes, sf)]
    else:
        raise InvalidArgumentError("one of size / scale_factor required")

    if mode == "nearest":
        return _resize_nearest(x, out_sizes, channel_last, align_corners)
    if mode in ("linear", "bilinear", "trilinear"):
        out = x
        for i in range(n_sp):
            out = _resize_linear_1d(out, out_sizes[i], spatial_start + i, align_corners, align_mode)
        return out
    if mode == "bicubic":
        # jax.image supports cubic resize
        import jax.image

        if channel_last:
            new_shape = (x.shape[0],) + tuple(out_sizes) + (x.shape[-1],)
        else:
            new_shape = x.shape[:2] + tuple(out_sizes)
        return jax.image.resize(x, new_shape, method="bicubic")
    if mode == "area":
        from .pooling import _adaptive_pool

        return _adaptive_pool(x, tuple(out_sizes), n_sp, channel_last, "avg")
    raise InvalidArgumentError(f"unknown interpolate mode {mode!r}")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format=None, name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    r = upscale_factor
    if data_format == "NCHW":
        N, C, H, W = x.shape
        oc = C // (r * r)
        out = x.reshape(N, oc, r, r, H, W)
        out = out.transpose(0, 1, 4, 2, 5, 3)
        return out.reshape(N, oc, H * r, W * r)
    N, H, W, C = x.shape
    oc = C // (r * r)
    out = x.reshape(N, H, W, r, r, oc)
    out = out.transpose(0, 1, 3, 2, 4, 5)
    return out.reshape(N, H * r, W * r, oc)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    r = downscale_factor
    if data_format == "NCHW":
        N, C, H, W = x.shape
        out = x.reshape(N, C, H // r, r, W // r, r)
        out = out.transpose(0, 1, 3, 5, 2, 4)
        return out.reshape(N, C * r * r, H // r, W // r)
    N, H, W, C = x.shape
    out = x.reshape(N, H // r, r, W // r, r, C)
    out = out.transpose(0, 1, 3, 2, 4, 5)
    return out.reshape(N, H // r, W // r, C * r * r)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    if data_format == "NCHW":
        N, C, H, W = x.shape
        out = x.reshape(N, groups, C // groups, H, W)
        return out.transpose(0, 2, 1, 3, 4).reshape(N, C, H, W)
    N, H, W, C = x.shape
    out = x.reshape(N, H, W, groups, C // groups)
    return out.transpose(0, 1, 2, 4, 3).reshape(N, H, W, C)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (ref: operators/unfold_op.cc, math/im2col.cu). Output layout
    matches paddle: (N, C*prod(kernel), L)."""
    x = jnp.asarray(x)
    N, C, H, W = x.shape
    k = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else tuple(kernel_sizes)
    s = (strides, strides) if isinstance(strides, int) else tuple(strides)
    d = (dilations, dilations) if isinstance(dilations, int) else tuple(dilations)
    if isinstance(paddings, int):
        p = (paddings,) * 4
    elif len(paddings) == 2:
        p = (paddings[0], paddings[1], paddings[0], paddings[1])
    else:
        p = tuple(paddings)
    xp = jnp.pad(x, [(0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])])
    out_h = (xp.shape[2] - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
    out_w = (xp.shape[3] - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
    patches = []
    for i in range(k[0]):
        for j in range(k[1]):
            sl = xp[:, :, i * d[0]: i * d[0] + out_h * s[0]: s[0],
                    j * d[1]: j * d[1] + out_w * s[1]: s[1]]
            patches.append(sl)
    # (k0*k1, N, C, out_h, out_w) → (N, C, k0*k1, L)
    stacked = jnp.stack(patches, axis=2)  # (N, C, k0*k1, oh, ow)
    return stacked.reshape(N, C * k[0] * k[1], out_h * out_w)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im inverse of unfold (sums overlaps)."""
    x = jnp.asarray(x)
    N = x.shape[0]
    oh, ow = output_sizes if isinstance(output_sizes, (list, tuple)) else (output_sizes, output_sizes)
    k = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else tuple(kernel_sizes)
    s = (strides, strides) if isinstance(strides, int) else tuple(strides)
    d = (dilations, dilations) if isinstance(dilations, int) else tuple(dilations)
    if isinstance(paddings, int):
        p = (paddings,) * 4
    elif len(paddings) == 2:
        p = (paddings[0], paddings[1], paddings[0], paddings[1])
    else:
        p = tuple(paddings)
    C = x.shape[1] // (k[0] * k[1])
    ph, pw = oh + p[0] + p[2], ow + p[1] + p[3]
    out_h = (ph - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
    out_w = (pw - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
    cols = x.reshape(N, C, k[0], k[1], out_h, out_w)
    canvas = jnp.zeros((N, C, ph, pw), x.dtype)
    for i in range(k[0]):
        for j in range(k[1]):
            canvas = canvas.at[:, :, i * d[0]: i * d[0] + out_h * s[0]: s[0],
                               j * d[1]: j * d[1] + out_w * s[1]: s[1]].add(cols[:, :, i, j])
    return canvas[:, :, p[0]: p[0] + oh, p[1]: p[1] + ow]


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    """Parity: fluid.layers.sequence_mask — the dense-masking primitive that
    replaces LoD ragged batching (SURVEY §5: LoD → padding+mask policy)."""
    lengths = jnp.asarray(lengths)
    if maxlen is None:
        maxlen = int(jnp.max(lengths))
    row = jnp.arange(maxlen)
    mask = row[None, :] < lengths[..., None]
    return mask.astype(_dt.convert_dtype(dtype))


def bilinear(x1, x2, weight, bias=None, name=None):
    """Parity: paddle.nn.functional.bilinear (ref: operators/bilinear_tensor_product_op.cc)."""
    x1 = jnp.asarray(x1)
    x2 = jnp.asarray(x2)
    w = jnp.asarray(weight)  # (out, in1, in2)
    out = jnp.einsum("bi,oij,bj->bo", x1, w, x2)
    if bias is not None:
        out = out + jnp.asarray(bias, out.dtype)
    return out


def affine_grid(theta, out_shape, align_corners=True, name=None):
    theta = jnp.asarray(theta)  # (N, 2, 3)
    N, C, H, W = out_shape

    def coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        return (jnp.arange(size) * 2 + 1) / size - 1.0

    ys = coords(H)
    xs = coords(W)
    gx, gy = jnp.meshgrid(xs, ys)  # (H, W)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # (H, W, 3)
    grid = jnp.einsum("hwi,nji->nhwj", base, theta)  # (N, H, W, 2)
    return grid


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    x = jnp.asarray(x)  # (N, C, H, W)
    grid = jnp.asarray(grid)  # (N, Ho, Wo, 2) in [-1, 1]
    N, C, H, W = x.shape

    def unnorm(g, size):
        if align_corners:
            return (g + 1) * (size - 1) / 2
        return ((g + 1) * size - 1) / 2

    gx = unnorm(grid[..., 0], W)
    gy = unnorm(grid[..., 1], H)

    if mode == "nearest":
        ix = jnp.clip(jnp.round(gx).astype(jnp.int32), 0, W - 1)
        iy = jnp.clip(jnp.round(gy).astype(jnp.int32), 0, H - 1)
        batch = jnp.arange(N)[:, None, None]
        return x[batch, :, iy, ix].transpose(0, 3, 1, 2)

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wx1 = (gx - x0).astype(x.dtype)
    wy1 = (gy - y0).astype(x.dtype)

    def sample(ix, iy):
        inb = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
        cx = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
        cy = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
        batch = jnp.arange(N)[:, None, None]
        v = x[batch, :, cy, cx]  # (N, Ho, Wo, C)
        if padding_mode == "zeros":
            v = jnp.where(inb[..., None], v, 0.0)
        return v

    v00 = sample(x0, y0)
    v01 = sample(x1, y0)
    v10 = sample(x0, y1)
    v11 = sample(x1, y1)
    out = (v00 * ((1 - wx1) * (1 - wy1))[..., None]
           + v01 * (wx1 * (1 - wy1))[..., None]
           + v10 * ((1 - wx1) * wy1)[..., None]
           + v11 * (wx1 * wy1)[..., None])
    return out.transpose(0, 3, 1, 2)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    NT, C, H, W = x.shape
    N = NT // seg_num
    x = x.reshape(N, seg_num, C, H, W)
    fold_c = int(C * shift_ratio)
    left = jnp.concatenate([x[:, 1:, :fold_c], jnp.zeros_like(x[:, :1, :fold_c])], axis=1)
    right = jnp.concatenate([jnp.zeros_like(x[:, :1, fold_c:2 * fold_c]), x[:, :-1, fold_c:2 * fold_c]], axis=1)
    rest = x[:, :, 2 * fold_c:]
    out = jnp.concatenate([left, right, rest], axis=2)
    return out.reshape(NT, C, H, W)


def npu_identity(x, format=-1):
    return jnp.asarray(x)

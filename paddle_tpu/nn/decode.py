"""Sequence decoding: ``Decoder`` / ``BeamSearchDecoder`` / ``dynamic_decode``.

Parity surface: paddle.nn.{BeamSearchDecoder, dynamic_decode} (reference:
python/paddle/fluid/layers/rnn.py:751 Decoder, :864 BeamSearchDecoder,
:1567 dynamic_decode; backtrace op operators/gather_tree_op.h:27).

TPU-native design: the reference builds a ``While`` op over a static
Program (declarative) or runs a Python loop with per-step array appends
(imperative).  Here the whole decode is ONE ``lax.while_loop`` with
preallocated ``[max_steps, ...]`` output buffers written by
``dynamic_update_index`` — XLA compiles a single early-exiting device
loop (stops as soon as every sequence is finished), and the function is
jit/vmap/shard-compatible.  Output step-structure is discovered with
``jax.eval_shape`` (no throwaway execution).

Semantic notes kept from the reference:
* ``decoder.tracks_own_finished`` — beam search reorders beams, so its
  own ``finished`` replaces (not ORs into) the loop tracker
  (rnn.py:1371-1379).
* finished-beam probability masking forces all mass onto ``end_token``
  (``_mask_probs``, rnn.py:1025).
* ``impute_finished`` freezes states of finished sequences using the
  pre-step finished mask (declarative path semantics, rnn.py:1508).
"""
from __future__ import annotations

import collections

import jax
import jax.numpy as jnp

from ..framework.errors import InvalidArgumentError
from .layer_base import Layer

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode",
           "DecodeHelper", "TrainingHelper", "GreedyEmbeddingHelper",
           "SampleEmbeddingHelper", "BasicDecoder"]

_KINF = 1e9


class Decoder:
    """Abstract decode-step provider for ``dynamic_decode`` (reference:
    fluid/layers/rnn.py:751).  Subclasses implement ``initialize`` /
    ``step`` / optionally ``finalize``; every method must be traceable
    (jnp ops, no data-dependent Python control flow) so the decode loop
    compiles to a single XLA while."""

    def initialize(self, inits):
        """→ (initial_inputs, initial_states, finished)."""
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        """→ (outputs, next_states, next_inputs, finished)."""
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        """→ (final_outputs, final_states); optional."""
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam-search decoding over a cell (reference: fluid/layers/rnn.py:864).

    The cell sees merged ``[batch*beam, ...]`` tensors; beam bookkeeping
    (score accumulation, finished masking, top-k over ``beam*vocab``,
    ancestor gathers) happens in ``[batch, beam, ...]`` — all dense jnp
    ops, so the whole step fuses into the decode while-loop.
    """

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)

    # -- shape plumbing ------------------------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] → [batch*beam, ...] with each entry repeated
        ``beam_size`` times (for attention memories etc., rnn.py:933)."""
        x = jnp.asarray(x)
        return jnp.repeat(x, beam_size, axis=0)

    def _split_batch_beams(self, x):
        x = jnp.asarray(x)
        return x.reshape((-1, self.beam_size) + x.shape[1:])

    def _merge_batch_beams(self, x):
        x = jnp.asarray(x)
        return x.reshape((-1,) + x.shape[2:])

    def _expand_to_beam_size(self, x):
        x = jnp.asarray(x)
        return jnp.broadcast_to(
            x[:, None], (x.shape[0], self.beam_size) + x.shape[1:])

    def _gather(self, x, indices):
        """x: [batch, beam, ...]; indices: [batch, beam] beam ids →
        reordered x (take_along_axis replaces the reference's
        coordinate-stack + gather_nd, rnn.py:1054)."""
        x = jnp.asarray(x)
        idx = indices.reshape(indices.shape + (1,) * (x.ndim - 2))
        return jnp.take_along_axis(x, idx.astype(jnp.int32), axis=1)

    # -- decode protocol ----------------------------------------------
    def initialize(self, initial_cell_states):
        states = jax.tree_util.tree_map(self._expand_to_beam_size,
                                        initial_cell_states)
        leaf = jax.tree_util.tree_leaves(initial_cell_states)[0]
        batch = leaf.shape[0]
        init_ids = jnp.full((batch, self.beam_size), self.start_token,
                            jnp.int64)
        # beam 0 live, the rest dead — standard first-step tie-break
        log_probs = jnp.tile(
            jnp.asarray([[0.0] + [-_KINF] * (self.beam_size - 1)],
                        jnp.float32), (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        lengths = jnp.zeros((batch, self.beam_size), jnp.int64)
        inputs = (self.embedding_fn(init_ids) if self.embedding_fn
                  else init_ids)
        return inputs, self.StateWrapper(states, log_probs, finished,
                                         lengths), finished

    def _beam_search_step(self, time, logits, next_cell_states, beam_state):
        vocab = logits.shape[-1]
        step_log_probs = jax.nn.log_softmax(
            jnp.asarray(logits, jnp.float32), axis=-1)
        # finished beams: all probability mass on end_token (rnn.py:1025)
        noend = jnp.full((vocab,), -_KINF, jnp.float32)
        noend = noend.at[self.end_token].set(0.0)
        step_log_probs = jnp.where(beam_state.finished[:, :, None], noend,
                                   step_log_probs)
        log_probs = step_log_probs + beam_state.log_probs[:, :, None]
        scores = log_probs.reshape(-1, self.beam_size * vocab)
        topk_scores, topk_indices = jax.lax.top_k(scores, self.beam_size)
        beam_indices = (topk_indices // vocab).astype(jnp.int64)
        token_indices = (topk_indices % vocab).astype(jnp.int64)
        next_log_probs = jnp.take_along_axis(scores, topk_indices, axis=1)
        next_cell_states = jax.tree_util.tree_map(
            lambda x: self._gather(x, beam_indices), next_cell_states)
        next_finished = self._gather(beam_state.finished, beam_indices)
        next_lengths = self._gather(beam_state.lengths, beam_indices)
        next_lengths = next_lengths + (~next_finished).astype(jnp.int64)
        next_finished = next_finished | (token_indices == self.end_token)
        output = self.OutputWrapper(topk_scores, token_indices, beam_indices)
        state = self.StateWrapper(next_cell_states, next_log_probs,
                                  next_finished, next_lengths)
        return output, state

    def step(self, time, inputs, states, **kwargs):
        inputs = jax.tree_util.tree_map(self._merge_batch_beams, inputs)
        cell_states = jax.tree_util.tree_map(self._merge_batch_beams,
                                             states.cell_states)
        cell_outputs, next_cell_states = self.cell(inputs, cell_states,
                                                   **kwargs)
        cell_outputs = jax.tree_util.tree_map(self._split_batch_beams,
                                              cell_outputs)
        next_cell_states = jax.tree_util.tree_map(self._split_batch_beams,
                                                  next_cell_states)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        output, state = self._beam_search_step(time, cell_outputs,
                                               next_cell_states, states)
        sample_ids = output.predicted_ids
        next_inputs = (self.embedding_fn(sample_ids) if self.embedding_fn
                       else sample_ids)
        return output, state, next_inputs, state.finished

    def finalize(self, outputs, final_states, sequence_lengths):
        from .functional.extension import gather_tree

        predicted_ids = gather_tree(outputs.predicted_ids,
                                    outputs.parent_ids)
        return predicted_ids, final_states

    def output_padding(self, out_shapes):
        """Buffer-tail padding for steps past all-finished early exit
        (consumed by dynamic_decode): exactly what a post-finish step
        would emit — EOS tokens, identity parents, zero scores — so the
        gather_tree backtrace passes straight through the tail rows.
        Without this, zero-filled parents would reroute every beam's
        ancestry through slot 0 under jit (where the tail can't be
        sliced off)."""
        batch, beam = out_shapes.parent_ids.shape
        return self.OutputWrapper(
            scores=jnp.zeros((batch, beam), out_shapes.scores.dtype),
            predicted_ids=jnp.full((batch, beam), self.end_token,
                                   out_shapes.predicted_ids.dtype),
            parent_ids=jnp.broadcast_to(
                jnp.arange(beam, dtype=out_shapes.parent_ids.dtype),
                (batch, beam)),
        )

    @property
    def tracks_own_finished(self):
        return True


def _transpose_batch_time(x):
    return jnp.swapaxes(jnp.asarray(x), 0, 1)


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run ``decoder`` until every sequence finishes or ``max_step_num``
    steps elapsed (reference: fluid/layers/rnn.py:1567).

    One ``lax.while_loop`` over preallocated output buffers — the loop
    exits early on the device when all sequences finish; under jit the
    time dimension of the outputs is ``max_step_num + 1`` (XLA static
    shapes), eagerly it is sliced to the steps actually executed, which
    matches the reference's dynamic-length outputs.
    """
    if max_step_num is None:
        max_step_num = 255  # reference decodes unbounded; XLA needs a cap
    max_steps = int(max_step_num) + 1  # ref loop runs until step > max

    initial_inputs, initial_states, initial_finished = decoder.initialize(
        inits)
    initial_finished = jnp.asarray(initial_finished)
    seq_len0 = jnp.zeros(initial_finished.shape, jnp.int64)

    # discover the per-step output structure without running a step
    out_shapes = jax.eval_shape(
        lambda i, s: decoder.step(jnp.asarray(0, jnp.int64), i, s,
                                  **kwargs)[0],
        initial_inputs, initial_states)
    # rows past the early exit keep their initial value (the loop never
    # writes them); let the decoder pick padding that means "decoding
    # already finished" — beam search needs identity parents + EOS ids
    # there or finalize's backtrace corrupts under jit
    pad = (decoder.output_padding(out_shapes)
           if hasattr(decoder, "output_padding") else
           jax.tree_util.tree_map(
               lambda sd: jnp.zeros(tuple(sd.shape), sd.dtype), out_shapes))
    out_bufs = jax.tree_util.tree_map(
        lambda p: jnp.broadcast_to(p, (max_steps,) + p.shape), pad)

    def cond(carry):
        time, _, _, finished, _, _ = carry
        return (time < max_steps) & ~jnp.all(finished)

    def body(carry):
        time, inputs, states, finished, seq_lens, bufs = carry
        outputs, next_states, next_inputs, step_finished = decoder.step(
            time, inputs, states, **kwargs)
        if decoder.tracks_own_finished:
            next_finished = jnp.asarray(step_finished)
        else:
            next_finished = jnp.asarray(step_finished) | finished
        # count this step for every sequence not ALREADY finished — the
        # EOS-emitting step is included (reference declarative path,
        # rnn.py:1502 adds ¬global_finished before updating it)
        next_seq_lens = seq_lens + (~finished).astype(jnp.int64)
        if impute_finished:  # freeze finished sequences' states
            next_states = jax.tree_util.tree_map(
                lambda old, new: jnp.where(
                    finished.reshape(finished.shape + (1,) *
                                     (jnp.asarray(new).ndim - finished.ndim)),
                    old, new),
                states, next_states)
        bufs = jax.tree_util.tree_map(
            lambda buf, o: jax.lax.dynamic_update_index_in_dim(
                buf, jnp.asarray(o, buf.dtype), time, axis=0),
            bufs, outputs)
        return (time + 1, next_inputs, next_states, next_finished,
                next_seq_lens, bufs)

    carry = (jnp.asarray(0, jnp.int64), initial_inputs, initial_states,
             initial_finished, seq_len0, out_bufs)
    time, _, final_states, _, sequence_lengths, out_bufs = (
        jax.lax.while_loop(cond, body, carry))

    if not isinstance(time, jax.core.Tracer):  # eager: true dynamic length
        steps = int(time)
        out_bufs = jax.tree_util.tree_map(lambda b: b[:steps], out_bufs)

    final_outputs = out_bufs
    try:
        final_outputs, final_states = decoder.finalize(
            final_outputs, final_states, sequence_lengths)
    except NotImplementedError:
        pass

    if not output_time_major:
        final_outputs = jax.tree_util.tree_map(_transpose_batch_time,
                                               final_outputs)
    return ((final_outputs, final_states, sequence_lengths)
            if return_length else (final_outputs, final_states))


class _DecodeHelperCell:
    """Adapter: a paddle RNN cell Layer → the (inputs, states) → (out,
    new_states) callable BeamSearchDecoder expects.  Layers already have
    that signature; this exists for callables needing kwargs bound."""

    def __init__(self, cell, **kwargs):
        self._cell = cell
        self._kwargs = kwargs

    def __call__(self, inputs, states):
        return self._cell(inputs, states, **self._kwargs)


# ---------------------------------------------------------------------------
# The sampling-helper family (reference: fluid/layers/rnn.py DecodeHelper
# :1659, TrainingHelper :1728, GreedyEmbeddingHelper :1881,
# SampleEmbeddingHelper :2012, BasicDecoder :2113) — the pre-2.0 seq2seq
# decode surface.  Every method is traceable, so BasicDecoder composes
# with dynamic_decode's single compiled while-loop.
# ---------------------------------------------------------------------------
class DecodeHelper:
    """Sampling protocol consumed by :class:`BasicDecoder`:
    ``initialize() -> (initial_inputs, initial_finished)``;
    ``sample(time, outputs, states) -> sample_ids``;
    ``next_inputs(time, outputs, states, sample_ids) ->
    (finished, next_inputs, next_states)``."""

    def initialize(self):
        raise NotImplementedError

    def sample(self, time, outputs, states):
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """Teacher forcing: feed the ground-truth sequence step by step
    (ref rnn.py:1728).  ``inputs``: [batch, T, ...] (or [T, batch, ...]
    with ``time_major``); ``sequence_length``: [batch] true lengths."""

    def __init__(self, inputs, sequence_length, time_major=False):
        self.inputs = inputs
        self.sequence_length = jnp.asarray(sequence_length)
        self.time_major = bool(time_major)
        self._axis = 0 if self.time_major else 1

    def _slice(self, t):
        ax = self._axis

        def take(x):
            x = jnp.asarray(x)
            tt = jnp.minimum(jnp.asarray(t, jnp.int32),
                             x.shape[ax] - 1)
            return jax.lax.dynamic_index_in_dim(x, tt, ax, keepdims=False)

        return jax.tree_util.tree_map(take, self.inputs)

    def initialize(self):
        return self._slice(0), self.sequence_length == 0

    def sample(self, time, outputs, states):
        return jnp.argmax(outputs, axis=-1).astype(jnp.int64)

    def next_inputs(self, time, outputs, states, sample_ids):
        next_time = jnp.asarray(time, jnp.int64) + 1
        finished = next_time >= self.sequence_length
        return finished, self._slice(next_time), states


class GreedyEmbeddingHelper(DecodeHelper):
    """Inference-time greedy sampling: argmax ids, re-embedded as the
    next step's input (ref rnn.py:1881).  ``embedding_fn`` maps
    [batch] int64 ids → inputs (use paddle.nn.Embedding / a lambda)."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = jnp.asarray(start_tokens, jnp.int64)
        self.end_token = jnp.asarray(int(end_token), jnp.int64)

    def initialize(self):
        finished = jnp.zeros(self.start_tokens.shape[:1], bool)
        return self.embedding_fn(self.start_tokens), finished

    def sample(self, time, outputs, states):
        return jnp.argmax(outputs, axis=-1).astype(jnp.int64)

    def next_inputs(self, time, outputs, states, sample_ids):
        finished = sample_ids == self.end_token
        return finished, self.embedding_fn(sample_ids), states


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Multinomial sampling from the per-step softmax (ref rnn.py:2012);
    ``softmax_temperature`` sharpens/flattens the distribution."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.temperature = softmax_temperature
        self._seed = seed
        self._key = (jax.random.PRNGKey(seed) if seed is not None
                     else None)

    def initialize(self):
        # unseeded: a FRESH key per decode run (two runs of the same
        # helper must sample differently, like the reference); a given
        # seed pins the whole run for reproducibility.  NOTE: under an
        # outer jax.jit this draw happens at trace time, so a jitted
        # decode function reuses one key across calls — seed explicitly
        # (or rebuild the helper) when wrapping dynamic_decode in jit.
        if self._seed is None:
            from ..framework import random as _prandom

            self._key = _prandom.default_generator().next_key()
        return super().initialize()

    def sample(self, time, outputs, states):
        logits = (outputs if self.temperature is None
                  else outputs / self.temperature)
        key = jax.random.fold_in(self._key, jnp.asarray(time, jnp.int32))
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int64)


class BasicDecoder(Decoder):
    """cell + helper composition (ref rnn.py:2113): one step = cell call
    → optional output_fn → helper.sample → helper.next_inputs; outputs
    are ``OutputWrapper(cell_outputs, sample_ids)`` per step."""

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("cell_outputs", "sample_ids"))

    def __init__(self, cell, helper: DecodeHelper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        initial_inputs, initial_finished = self.helper.initialize()
        return initial_inputs, initial_cell_states, initial_finished

    def step(self, time, inputs, states, **kwargs):
        cell_outputs, cell_states = self.cell(inputs, states, **kwargs)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        sample_ids = self.helper.sample(time, cell_outputs, cell_states)
        finished, next_inputs, next_states = self.helper.next_inputs(
            time, cell_outputs, cell_states, sample_ids)
        return (BasicDecoder.OutputWrapper(cell_outputs, sample_ids),
                next_states, next_inputs, finished)

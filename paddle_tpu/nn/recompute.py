"""Recompute (activation checkpointing / rematerialization).

Capability parity: RecomputeOptimizer
(reference: python/paddle/fluid/optimizer.py:4547 and the recompute-aware
backward builder backward.py:689 `_append_backward_ops_with_checkpoints_`).
The reference rewrites the static Program so the backward pass regenerates
segment activations from user-marked checkpoint variables.

TPU-native design: ``jax.checkpoint`` (remat) on a per-block function does
the same inside one jitted step — the forward residuals of the wrapped
block are dropped and recomputed during the backward sweep, trading ~1/3
extra FLOPs for O(depth → sqrt) activation memory.  RNG-consuming ops
(dropout) stay consistent between the two sweeps because the traced key
operand is replayed, not re-drawn.
"""
from __future__ import annotations

from collections import Counter
from typing import Iterable, Optional

import jax

from .container import LayerList, Sequential
from .layer_base import Layer

__all__ = ["recompute", "mark_recompute", "apply_recompute"]

_POLICIES = {
    None: None,
    "none": None,  # full remat: save nothing inside the block
    "dots": "dots_with_no_batch_dims_saveable",
    "dots_saveable": "dots_saveable",
    "everything_saveable": "everything_saveable",
}


def _resolve_policy(policy):
    if callable(policy):
        return policy
    name = _POLICIES.get(policy, policy)
    if name is None:
        return None
    return getattr(jax.checkpoint_policies, name)


def recompute(function, *args, policy=None, **kwargs):
    """Run ``function(*args)`` under rematerialization.

    Parity: paddle.distributed.fleet.utils.recompute(function, *args) —
    same call-then-recompute-in-backward semantics, via jax.checkpoint
    instead of a program rewrite.
    """
    pol = _resolve_policy(policy)

    def run(args, kwargs):  # fresh closure per call — see mark_recompute
        return function(*args, **kwargs)

    fn = jax.checkpoint(run, policy=pol) if pol is not None else jax.checkpoint(run)
    return fn(args, kwargs)


def mark_recompute(layer: Layer, policy=None) -> Layer:
    """Wrap one Layer's forward in jax.checkpoint (idempotent).

    A FRESH checkpointed closure is built per call: jax.checkpoint caches
    the traced jaxpr per function object, and our forward closes over
    Parameter-box tracers that change between jit traces — reusing one
    wrapped function across steps would replay stale tracers
    (UnexpectedTracerError).  Wrapping only happens at trace time, so the
    retrace cost is once per compilation, not per step.
    """
    if getattr(layer, "_recompute_wrapped", False):
        return layer
    pol = _resolve_policy(policy)
    orig = layer.forward

    def forward_with_remat(*args, **kwargs):
        def run(args, kwargs):
            return orig(*args, **kwargs)

        fn = jax.checkpoint(run, policy=pol) if pol is not None else jax.checkpoint(run)
        return fn(args, kwargs)

    layer.forward = forward_with_remat
    layer._recompute_wrapped = True
    layer._recompute_orig_forward = orig
    return layer


def unmark_recompute(layer: Layer) -> Layer:
    if getattr(layer, "_recompute_wrapped", False):
        layer.forward = layer._recompute_orig_forward
        del layer._recompute_orig_forward
        layer._recompute_wrapped = False
    return layer


def _repeated_blocks(network: Layer):
    """Default checkpoint segmentation: the children of any LayerList /
    Sequential in which one class repeats ≥2× (transformer blocks, ResNet
    stages…) — the same granularity users of the reference mark with
    ``checkpoints=`` per segment."""
    blocks = []
    for sub in network.sublayers(include_self=True):
        if isinstance(sub, (LayerList, Sequential)):
            children = list(sub)
            counts = Counter(type(c) for c in children)
            for child in children:
                if isinstance(child, Layer) and counts[type(child)] >= 2:
                    blocks.append(child)
    return blocks


def apply_recompute(network: Layer, layer_classes: Optional[Iterable[str]] = None,
                    policy=None) -> int:
    """Wrap matching sublayers for recompute; returns how many were wrapped.

    ``layer_classes``: class names to wrap (e.g. ["GPTBlock"]); default =
    repeated block heuristic (see _repeated_blocks).
    """
    if layer_classes:
        wanted = set(layer_classes)
        targets = [l for l in network.sublayers(include_self=True)
                   if type(l).__name__ in wanted]
    else:
        targets = _repeated_blocks(network)
    for layer in targets:
        mark_recompute(layer, policy=policy)
    return len(targets)

"""paddle_tpu.nn — neural network layers (paddle.nn parity).

Reference surface: python/paddle/nn/ (19.5k LoC of Layer classes).  See
layer_base.py for the TPU-native Layer/autodiff design.
"""
from .layer_base import (  # noqa: F401
    Layer,
    Parameter,
    Buffer,
    functional_call,
    current_rng_key,
    rng_scope,
)
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .initializer import ParamAttr  # noqa: F401

from . import recompute as _recompute_mod  # noqa: F401
from .recompute import apply_recompute, mark_recompute, recompute  # noqa: F401

from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .activation import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .container import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403
from .transformer import *  # noqa: F401,F403
from .decode import *  # noqa: F401,F403
from ..optimizer.clip import (  # noqa: F401  (paddle.nn re-exports clips)
    ClipGradByValue,
    ClipGradByNorm,
    ClipGradByGlobalNorm,
)
from . import utils  # noqa: F401
from . import utils as weight_norm_hook  # noqa: F401  (ref nn/__init__.py:22)
from .utils import weight_norm, remove_weight_norm  # noqa: F401
from .functional import extension  # noqa: F401  (ref nn/__init__.py:19)
from . import vision  # noqa: F401  (ref nn/__init__.py:160 layer.vision)


from ..tensor.math import clip  # noqa: F401  (ref: nn/clip.py:38 re-export)


def clip_by_norm(x, max_norm, name=None):
    """L2-norm clip: ``x·max_norm/max(‖x‖, max_norm)`` (ref: nn/clip.py:39
    ← fluid/layers/nn.py:12375 over operators/clip_by_norm_op.h)."""
    import jax.numpy as jnp

    x = jnp.asarray(x)
    norm = jnp.sqrt(jnp.sum(x * x))
    return x * (max_norm / jnp.maximum(norm, max_norm))

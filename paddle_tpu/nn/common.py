"""Common layers: Linear, Embedding, Dropout, Flatten, padding, upsample…

Parity surface: paddle.nn (reference: python/paddle/nn/layer/common.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from . import functional as F
from . import initializer as I
from .layer_base import Layer, Parameter

__all__ = [
    "Linear", "Identity", "Embedding", "Dropout", "Dropout2D", "Dropout3D",
    "AlphaDropout", "Flatten", "Unflatten", "Upsample", "UpsamplingBilinear2D",
    "UpsamplingNearest2D", "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D",
    "CosineSimilarity", "Bilinear", "PixelShuffle", "PixelUnshuffle",
    "ChannelShuffle", "Fold", "Unfold", "PairwiseDistance", "RowConv",
    "BilinearTensorProduct", "Pool2D",
]


class Linear(Layer):
    """Parity: paddle.nn.Linear — weight layout (in_features, out_features)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        if bias_attr is not False:
            self.bias = self.create_parameter((out_features,), attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight.value,
                        self.bias.value if self.bias is not None else None)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Embedding(Layer):
    """Parity: paddle.nn.Embedding (ref: operators/lookup_table_v2_op).

    ``sparse=True`` marks the table for SelectedRows gradients: inside a
    sparse-aware train step (hapi.Model builds one automatically) the
    backward produces an O(touched-rows) ``(ids, rows)`` gradient instead of
    a dense O(vocab) cotangent, and lazy-mode optimizers update only the
    touched rows — see framework/selected_rows.py (ref:
    paddle/fluid/framework/selected_rows.h:41).  Outside such a step the
    flag is inert and gradients are dense (XLA scatter-add).

    CONTRACT: with ``sparse=True`` the table receives gradients ONLY
    through embedding lookups (this layer's forward).  Any other read of
    ``weight`` — tied output heads, explicit regularization terms, custom
    matmuls — trains it as a constant for that use (same as the reference,
    where SelectedRows grads exist only for lookup_table ops).  Keep
    ``sparse=False`` for tied-weight tables."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.sparse = sparse
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        self.weight.sparse = bool(sparse)
        if padding_idx is not None:
            self.weight.value = self.weight.value.at[padding_idx].set(0.0)

    def forward(self, x):
        if self.sparse:
            from ..framework.selected_rows import tap_lookup

            rows = tap_lookup(self.weight, self.weight.value, x,
                              self.num_embeddings,
                              padding_idx=self.padding_idx)
            if rows is not None:
                return rows
        return F.embedding(x, self.weight.value, padding_idx=self.padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ..tensor.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = tuple(shape)

    def forward(self, x):
        x = jnp.asarray(x)
        ax = self.axis % x.ndim
        return x.reshape(x.shape[:ax] + self.shape + x.shape[ax + 1:])


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format=None, name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding if isinstance(self.padding, (list, tuple))
                     else [self.padding] * self._n_pad, mode=self.mode,
                     value=self.value, data_format=self.data_format)


class Pad1D(_PadNd):
    _n_pad = 2


class Pad2D(_PadNd):
    _n_pad = 4


class Pad3D(_PadNd):
    _n_pad = 6


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr)
        if bias_attr is not False:
            self.bias = self.create_parameter((out_features,), attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight.value,
                          self.bias.value if self.bias is not None else None)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class PairwiseDistance(Layer):
    """p-norm distance between paired rows (ref: nn/layer/distance.py:24
    PairwiseDistance over the p_norm op)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        from ..tensor.linalg import norm

        d = jnp.asarray(x) - jnp.asarray(y) + self.epsilon
        return norm(d, p=self.p, axis=-1, keepdim=self.keepdim)


class RowConv(Layer):
    """Lookahead row convolution layer (ref: nn/layer/extension RowConv
    over operators/row_conv_op.cc); weight [future_context_size+1, D]."""

    def __init__(self, num_channels, future_context_size, activation=None,
                 param_attr=None, name=None):
        super().__init__()
        self.activation = activation
        self.weight = self.create_parameter(
            [future_context_size + 1, num_channels], attr=param_attr)

    def forward(self, x):
        return F.row_conv(x, self.weight.value, act=self.activation)


class BilinearTensorProduct(Layer):
    """Legacy bilinear layer (ref: fluid/dygraph/nn.py BilinearTensorProduct
    / nn/__init__.py:74): ``out_i = act(x W_i y^T + b_i)`` — the 2.0
    ``Bilinear`` math plus the built-in activation of the 1.x API."""

    def __init__(self, input1_dim, input2_dim, output_dim, name=None,
                 act=None, param_attr=None, bias_attr=None):
        super().__init__()
        self.act = act
        self.weight = self.create_parameter(
            (output_dim, input1_dim, input2_dim), attr=param_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter((1, output_dim), attr=bias_attr,
                                           is_bias=True))

    def forward(self, x, y):
        out = F.bilinear(x, y, self.weight.value,
                         self.bias.value if self.bias is not None else None)
        if self.act:
            out = getattr(F, self.act)(out)
        return out


class Pool2D(Layer):
    """Legacy pooling layer (ref: fluid/dygraph/nn.py Pool2D /
    nn/__init__.py:75) — thin driver over the 2.0 functional pools; the
    1.x knobs (global_pooling, exclusive, ceil_mode) map directly."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, data_format="NCHW"):
        super().__init__()
        from ..framework.errors import InvalidArgumentError

        if pool_type not in ("max", "avg"):
            raise InvalidArgumentError(
                f"pool_type must be 'max' or 'avg', got {pool_type!r}")
        if not global_pooling and pool_size == -1:
            raise InvalidArgumentError(
                "Pool2D: pool_size must be set when global_pooling is "
                "False (the -1 default only makes sense with "
                "global_pooling=True)")
        self.pool_size = pool_size
        self.pool_type = pool_type
        self.pool_stride = pool_stride
        self.pool_padding = pool_padding
        self.global_pooling = global_pooling
        self.ceil_mode = ceil_mode
        self.exclusive = exclusive
        self.data_format = data_format

    def forward(self, x):
        x = jnp.asarray(x)
        if self.global_pooling:
            axes = (2, 3) if self.data_format == "NCHW" else (1, 2)
            red = jnp.max if self.pool_type == "max" else jnp.mean
            return red(x, axis=axes, keepdims=True)
        if self.pool_type == "max":
            return F.max_pool2d(x, self.pool_size, stride=self.pool_stride,
                                padding=self.pool_padding,
                                ceil_mode=self.ceil_mode,
                                data_format=self.data_format)
        return F.avg_pool2d(x, self.pool_size, stride=self.pool_stride,
                            padding=self.pool_padding,
                            ceil_mode=self.ceil_mode,
                            exclusive=self.exclusive,
                            data_format=self.data_format)

"""Transformer layers.

Parity surface: paddle.nn.{MultiHeadAttention,TransformerEncoderLayer,
TransformerEncoder,TransformerDecoderLayer,TransformerDecoder,Transformer}
(reference: python/paddle/nn/layer/transformer.py).

TPU-native: attention runs through
``paddle_tpu.nn.functional.scaled_dot_product_attention`` which routes long
sequences to the Pallas flash-attention kernel; QKV projections are three
MXU matmuls XLA fuses; everything is bf16-friendly.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import functional as F
from .common import Linear, Dropout
from .container import LayerList
from .layer_base import Layer
from .norm import LayerNorm

__all__ = [
    "MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
    "TransformerDecoderLayer", "TransformerDecoder", "Transformer",
]


def _convert_attn_mask(mask, dtype):
    """paddle convention: bool mask True=keep; float mask added to logits."""
    if mask is None:
        return None
    mask = jnp.asarray(mask)
    if mask.dtype == jnp.bool_:
        return mask
    return mask.astype(dtype)


class MultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self._cache = None

    def _shape(self, x):
        # (B, S, E) → (B, S, H, D)
        b, s, _ = x.shape
        return x.reshape(b, s, self.num_heads, self.head_dim)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        query = jnp.asarray(query)
        key = query if key is None else jnp.asarray(key)
        value = key if value is None else jnp.asarray(value)
        q = self._shape(self.q_proj(query))
        k = self._shape(self.k_proj(key))
        v = self._shape(self.v_proj(value))
        if cache is not None:
            # incremental decode: concat past K/V (paddle Cache parity)
            pk, pv = cache
            k = jnp.concatenate([pk, k], axis=1)
            v = jnp.concatenate([pv, v], axis=1)
            new_cache = (k, v)
        mask = _convert_attn_mask(attn_mask, q.dtype)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout,
            training=self.training)
        b, s, _, _ = out.shape
        out = self.out_proj(out.reshape(b, s, self.embed_dim))
        if cache is not None:
            return out, new_cache
        return out

    def gen_cache(self, key, value=None, type=None):
        """Start an empty decode cache (paddle parity shape)."""
        key = jnp.asarray(key)
        b = key.shape[0]
        empty = jnp.zeros((b, 0, self.num_heads, self.head_dim), key.dtype)
        return (empty, empty)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout if attn_dropout is not None else dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = activation

    def _act(self, x):
        return getattr(F, self.activation)(x)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        x = self.norm1(src) if self.normalize_before else src
        x = self.self_attn(x, attn_mask=src_mask)
        x = residual + self.dropout1(x)
        if not self.normalize_before:
            x = self.norm1(x)
        residual = x
        y = self.norm2(x) if self.normalize_before else x
        y = self.linear2(self.act_dropout(self._act(self.linear1(y))))
        y = residual + self.dropout2(y)
        if not self.normalize_before:
            y = self.norm2(y)
        return y


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] +
                                [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=ad,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=ad,
                                             weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = activation

    def _act(self, x):
        return getattr(F, self.activation)(x)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        x = self.norm1(tgt) if self.normalize_before else tgt
        x = self.self_attn(x, attn_mask=tgt_mask)
        x = residual + self.dropout1(x)
        if not self.normalize_before:
            x = self.norm1(x)
        residual = x
        y = self.norm2(x) if self.normalize_before else x
        y = self.cross_attn(y, memory, memory, attn_mask=memory_mask)
        y = residual + self.dropout2(y)
        if not self.normalize_before:
            y = self.norm2(y)
        residual = y
        z = self.norm3(y) if self.normalize_before else y
        z = self.linear2(self.act_dropout(self._act(self.linear1(z))))
        z = residual + self.dropout3(z)
        if not self.normalize_before:
            z = self.norm3(z)
        return z


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] +
                                [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    """Parity: paddle.nn.Transformer."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        return jnp.tril(jnp.ones((length, length), bool))

"""Layer/Parameter system + the eager↔functional bridge.

TPU-native re-design of the reference's module system:

* ``paddle.nn.Layer`` (reference: python/paddle/fluid/dygraph/layers.py) —
  parameter/buffer/sublayer registration, name scopes, train/eval,
  state_dict.  Reproduced here with the same ergonomics.
* dygraph Tracer + BasicEngine autograd (paddle/fluid/imperative/tracer.cc,
  basic_engine.cc) — NOT reproduced.  Instead ``functional_call`` projects a
  stateful Layer onto a pure function of a parameter pytree, so ``jax.grad``
  / ``jax.jit`` / ``jax.vmap`` provide autodiff and compilation.  This is the
  single-runtime answer to the reference's dual static/dygraph engines: the
  eager API *is* the traceable API.

A ``Parameter`` is a mutable box over a ``jax.Array`` implementing
``__jax_array__``, so ``jnp.matmul(x, layer.weight)`` works directly in
forward() while the optimizer can still rebind values in-place (eager mode)
and ``functional_call`` can substitute tracers (jit mode).
"""
from __future__ import annotations

import contextlib
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as _random
from ..framework.errors import InvalidArgumentError, NotFoundError
# amp only imports framework/jax at module level — no cycle back into nn
from ..amp.auto_cast import amp_state as _amp_state
from ..amp.auto_cast import cast_layer_call as _amp_cast_layer_call

__all__ = [
    "Parameter",
    "Buffer",
    "Layer",
    "functional_call",
    "current_rng_key",
    "rng_scope",
]


class Parameter:
    """Trainable tensor box. ``trainable=False`` ≙ paddle's stop_gradient.

    ``partition_spec`` (tuple of mesh axis names / None per dim, or None for
    replicated) is the tensor-parallel placement annotation consumed by
    distributed.fleet.ShardingPlan."""

    __slots__ = ("value", "name", "trainable", "partition_spec", "sparse")

    def __init__(self, value, name: str = "", trainable: bool = True,
                 partition_spec=None):
        self.value = jnp.asarray(value)
        self.name = name
        self.trainable = trainable
        self.partition_spec = partition_spec
        # sparse=True: gradients flow as SelectedRows through sparse-aware
        # train steps (framework/selected_rows.py); set by
        # nn.Embedding(sparse=True)
        self.sparse = False

    # jnp.asarray(param) → the underlying array; makes params usable in ops.
    def __jax_array__(self):
        return self.value

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def ndim(self):
        return self.value.ndim

    def numpy(self):
        return np.asarray(self.value)

    def set_value(self, v):
        self.value = jnp.asarray(v, dtype=self.value.dtype)

    def __repr__(self):
        return f"Parameter(name={self.name!r}, shape={self.shape}, dtype={self.dtype}, trainable={self.trainable})"

    # arithmetic conveniences (rarely needed; forward code usually passes
    # the box straight into jnp ops)
    def __mul__(self, o):
        return self.value * o

    def __rmul__(self, o):
        return o * self.value

    def __add__(self, o):
        return self.value + o

    def __radd__(self, o):
        return o + self.value

    def __sub__(self, o):
        return self.value - o

    def __neg__(self):
        return -self.value

    def __getitem__(self, idx):
        return self.value[idx]

    def astype(self, dt):
        return self.value.astype(dt)


class Buffer(Parameter):
    """Non-trainable state (BN running stats). Parity: Layer.register_buffer.
    persistable=False buffers are excluded from state_dict."""

    __slots__ = ("persistable",)

    def __init__(self, value, name: str = "", persistable: bool = True):
        super().__init__(value, name, trainable=False)
        self.persistable = persistable


# ---------------------------------------------------------------------------
# RNG plumbing: eager mode pulls from the global generator; functional mode
# installs a per-call key via rng_scope so traced dropout is pure.
# ---------------------------------------------------------------------------
class _RngState(threading.local):
    def __init__(self):
        self.stack = []


_rng_state = _RngState()


class _RngCtx:
    __slots__ = ("key", "count")

    def __init__(self, key):
        self.key = key
        self.count = 0

    def next(self):
        k = jax.random.fold_in(self.key, self.count)
        self.count += 1
        return k


@contextlib.contextmanager
def rng_scope(key):
    """Install an explicit RNG key for all random layers inside the scope."""
    ctx = _RngCtx(key)
    _rng_state.stack.append(ctx)
    try:
        yield ctx
    finally:
        _rng_state.stack.pop()


def current_rng_key() -> jax.Array:
    """Key for a random op inside a Layer.forward. Deterministic per-call
    inside rng_scope (traced mode); fresh from the global generator otherwise."""
    if _rng_state.stack:
        return _rng_state.stack[-1].next()
    return _random.default_generator().next_key()


# ---------------------------------------------------------------------------
# Layer
# ---------------------------------------------------------------------------
def build_parameter(shape, dtype=None, attr=None, is_bias=False,
                    default_initializer=None) -> "Parameter":
    """Create a Parameter box from ParamAttr semantics — shared by
    Layer.create_parameter and the top-level paddle.create_parameter
    (ref: fluid/layers/tensor.py:75), so initializer precedence, dtype
    defaulting, and the trainable flag cannot drift between the two."""
    from . import initializer as I
    from ..framework import dtype as _dt

    dtype = _dt.convert_dtype(dtype or _dt.get_default_dtype())
    init = None
    name = None
    trainable = True
    if attr is not None and attr is not False:
        init = getattr(attr, "initializer", None)
        name = getattr(attr, "name", None)
        trainable = getattr(attr, "trainable", True)
    if init is None:
        init = default_initializer or (
            I.Constant(0.0) if is_bias else I.XavierNormal())
    value = init(tuple(shape), dtype, key=_random.default_generator().next_key())
    return Parameter(value, name=name or "", trainable=trainable)


class Layer:
    """Parity: paddle.nn.Layer (python/paddle/fluid/dygraph/layers.py).

    Differences by design (TPU-native):
      * no ``.backward()`` — use ``functional_call`` + jax.grad (or the
        hapi ``Model``/fleet APIs which do it for you);
      * buffers mutated in forward (BN stats) are captured functionally by
        ``functional_call(..., return_buffers=True)`` when traced.
    """

    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._buffers: "OrderedDict[str, Buffer]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self.training = True
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._dtype = dtype

    # -- registration --------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        if isinstance(value, Parameter) and not isinstance(value, Buffer):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
            return
        if isinstance(value, Buffer):
            self._buffers[name] = value
            self.__dict__.pop(name, None)
            return
        if isinstance(value, Layer):
            subs = self.__dict__.get("_sub_layers")
            if subs is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            subs[name] = value
            self.__dict__.pop(name, None)
            return
        # assigning a plain value (incl. None) over a registered name must
        # evict the registry entry, or state_dict/param_pytree would keep
        # emitting a dead parameter (paddle Layer.__setattr__ does the same)
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name: str, param: Optional[Parameter]) -> Optional[Parameter]:
        if param is None:
            self._parameters[name] = None  # type: ignore[assignment]
            return None
        if not isinstance(param, Parameter):
            param = Parameter(param, name=name)
        self._parameters[name] = param
        return param

    def register_buffer(self, name: str, tensor, persistable: bool = True):
        buf = Buffer(tensor, name=name, persistable=persistable)
        self._buffers[name] = buf
        return buf

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        return sublayer

    def create_parameter(self, shape, dtype=None, attr=None, is_bias=False,
                         default_initializer=None):
        """Parity: Layer.create_parameter (dygraph/layers.py). Uses ParamAttr
        semantics from paddle.ParamAttr."""
        return build_parameter(shape, dtype or self._dtype, attr, is_bias,
                               default_initializer)

    # -- traversal -----------------------------------------------------------
    def named_sublayers(self, prefix: str = "", include_self: bool = False) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield p, sub
            yield from sub.named_sublayers(prefix=p)

    def sublayers(self, include_self: bool = False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True) -> Iterator[Tuple[str, Parameter]]:
        for name, p in self._parameters.items():
            if p is not None:
                dotted = f"{prefix}.{name}" if prefix else name
                if not p.name:
                    # stamp the dotted path as the box's stable identity:
                    # eager optimizer.step() matches jax.grad's name-keyed
                    # grad dicts against box names (a positional zip is
                    # unsound — jax returns dict pytrees in sorted-key
                    # order, not traversal order)
                    p.name = dotted
                yield dotted, p
        if include_sublayers:
            for sname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sp = f"{prefix}.{sname}" if prefix else sname
                yield from sub.named_parameters(prefix=sp)

    def parameters(self, include_sublayers: bool = True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True) -> Iterator[Tuple[str, Buffer]]:
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for sname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sp = f"{prefix}.{sname}" if prefix else sname
                yield from sub.named_buffers(prefix=sp)

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    # -- mode ----------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn: Callable[["Layer"], None]):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- dtype/device --------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=True):
        from ..framework import dtype as _dt

        if dtype is not None:
            nd = _dt.convert_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p.value.dtype, jnp.floating):
                    p.value = p.value.astype(nd)
            for b in self.buffers():
                if jnp.issubdtype(b.value.dtype, jnp.floating):
                    b.value = b.value.astype(nd)
        if device is not None:
            dev = device.jax_device() if hasattr(device, "jax_device") else device
            for p in self.parameters():
                p.value = jax.device_put(p.value, dev)
            for b in self.buffers():
                b.value = jax.device_put(b.value, dev)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    # -- state ---------------------------------------------------------------
    def state_dict(self, include_sublayers=True, keep_vars=False) -> "OrderedDict[str, Any]":
        out: "OrderedDict[str, Any]" = OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            out[name] = p if keep_vars else p.value
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            if getattr(b, "persistable", True):
                out[name] = b if keep_vars else b.value
        return out

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name: bool = True):
        """Parity: Layer.set_state_dict / load_dict."""
        own = dict(self.named_parameters())
        own.update(dict(self.named_buffers()))
        missing = []
        for name, value in state_dict.items():
            if name in own:
                tgt = own[name]
                value = jnp.asarray(value)
                if tuple(tgt.value.shape) != tuple(value.shape):
                    raise InvalidArgumentError(
                        f"shape mismatch for {name}: have {tuple(tgt.value.shape)}, "
                        f"loading {tuple(value.shape)}"
                    )
                tgt.value = value.astype(tgt.value.dtype)
            else:
                missing.append(name)
        return missing

    load_dict = set_state_dict

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        hid = len(self._forward_pre_hooks)
        self._forward_pre_hooks[hid] = hook
        return _HookRemover(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook):
        hid = len(self._forward_post_hooks)
        self._forward_post_hooks[hid] = hook
        return _HookRemover(self._forward_post_hooks, hid)

    # -- call ----------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        if _amp_state().enabled:
            with _amp_cast_layer_call(self, args, kwargs) as (args, kwargs):
                return self._call_impl(args, kwargs)
        return self._call_impl(args, kwargs)

    def _call_impl(self, args, kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, args)
            if result is not None:
                args = result if isinstance(result, tuple) else (result,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, args, out)
            if result is not None:
                out = result
        return out

    def __repr__(self):
        lines = [self.__class__.__name__ + "("]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else f"{self.__class__.__name__}()"

    # -- functional projection ----------------------------------------------
    def param_pytree(self, trainable_only: bool = False) -> Dict[str, jax.Array]:
        """Flat {dotted_name: value} pytree of parameters."""
        return {
            n: p.value
            for n, p in self.named_parameters()
            if (p.trainable or not trainable_only)
        }

    def buffer_pytree(self) -> Dict[str, jax.Array]:
        return {n: b.value for n, b in self.named_buffers()}


class _HookRemover:
    def __init__(self, store, hid):
        self._store = store
        self._hid = hid

    def remove(self):
        self._store.pop(self._hid, None)


# ---------------------------------------------------------------------------
# functional_call — project a Layer onto a pure function
# ---------------------------------------------------------------------------
def functional_call(
    layer: Layer,
    params: Dict[str, jax.Array],
    *args,
    buffers: Optional[Dict[str, jax.Array]] = None,
    rngs: Optional[jax.Array] = None,
    training: Optional[bool] = None,
    return_buffers: bool = False,
    call: Optional[Callable] = None,
    **kwargs,
):
    """Run ``layer(*args, **kwargs)`` with parameter/buffer values substituted
    from pytrees — pure w.r.t. ``params``/``buffers``/``rngs`` and therefore
    safe under jit/grad/vmap.

    Replaces the reference's static-graph Program construction: instead of
    building an OpDesc graph and calling append_backward
    (python/paddle/fluid/backward.py:1275), we trace the eager forward.

    Returns ``out`` or ``(out, new_buffers)`` when ``return_buffers=True``
    (captures BN running-stat updates made during the call).  With
    ``return_buffers=True`` ALL buffer boxes are restored to their entry
    values afterwards — the updates are returned functionally, never left
    behind (a traced call must not leak tracers into eager state).  Without
    it, in-forward buffer mutation persists (eager paddle semantics).

    ``call`` overrides the invoked callable (still runs with the layer's
    values substituted) — jit.to_static uses it for @to_static-decorated
    bound methods, where calling ``layer(...)`` would re-enter the wrapper.
    """
    boxes: Dict[str, Parameter] = dict(layer.named_parameters())
    buf_boxes: Dict[str, Buffer] = dict(layer.named_buffers())

    saved_vals = {}
    saved_training = None

    try:
        # snapshot EVERY param box, not just the substituted ones: derived
        # params (e.g. the weight_norm cache, nn/utils.py) are rewritten by
        # pre-hooks during the traced call and must not leak tracers into
        # eager state
        for name, box in boxes.items():
            saved_vals[("p", name)] = box.value
        for name, value in params.items():
            box = boxes.get(name)
            if box is None:
                raise NotFoundError(f"no parameter named {name!r} in {type(layer).__name__}")
            box.value = value
        if return_buffers:
            for name, box in buf_boxes.items():
                saved_vals[("b", name)] = box.value
        if buffers:
            for name, value in buffers.items():
                box = buf_boxes.get(name)
                if box is None:
                    raise NotFoundError(f"no buffer named {name!r}")
                saved_vals.setdefault(("b", name), box.value)
                box.value = value
        if training is not None:
            saved_training = [(l, l.training) for l in layer.sublayers(include_self=True)]
            for l, _ in saved_training:
                l.training = training

        ctx = rng_scope(rngs) if rngs is not None else contextlib.nullcontext()
        with ctx:
            out = (layer if call is None else call)(*args, **kwargs)

        if return_buffers:
            new_buffers = {n: b.value for n, b in buf_boxes.items()}
            return out, new_buffers
        return out
    finally:
        for (kind, name), v in saved_vals.items():
            (boxes if kind == "p" else buf_boxes)[name].value = v
        if saved_training is not None:
            for l, t in saved_training:
                l.training = t

"""paddle.nn.utils — weight normalization hooks.

Parity: python/paddle/nn/utils/weight_norm_hook.py (WeightNorm:93,
weight_norm:155, remove_weight_norm:203).  Reparameterizes a layer's
weight as ``w = g * v / ||v||`` (norm over every dim except ``dim``).

TPU-native: the recompute runs as a forward pre-hook *inside* the traced
call, after ``functional_call`` substitutes ``<name>_g``/``<name>_v`` —
so gradients flow to g and v, and the derived ``<name>`` box is a
non-trainable cache the optimizer skips (Parameter.trainable=False ≙
stop_gradient).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.errors import InvalidArgumentError
from .layer_base import Layer, Parameter

__all__ = ["weight_norm", "remove_weight_norm"]


def _norm_except_dim(v, dim):
    """L2 norm over all axes but ``dim`` (ref: weight_norm_hook.py:45
    norm_except_dim); dim=-1 → scalar full norm."""
    v = jnp.asarray(v)
    if dim == -1:
        return jnp.sqrt(jnp.sum(v * v) + 1e-12)
    moved = jnp.moveaxis(v, dim, 0).reshape(v.shape[dim], -1)
    return jnp.sqrt(jnp.sum(moved * moved, axis=1) + 1e-12)


def _weight_from_gv(g, v, dim):
    v = jnp.asarray(v)
    g = jnp.asarray(g)
    if dim == -1:
        return v / (jnp.sqrt(jnp.sum(v * v)) + 1e-12) * g
    norm = _norm_except_dim(v, dim)
    shape = [1] * v.ndim
    shape[dim] = v.shape[dim]
    return v / norm.reshape(shape) * (g.reshape(shape))


class WeightNorm:
    """The registered pre-hook object (ref: weight_norm_hook.py:93)."""

    def __init__(self, name, dim):
        self.name = name
        self.dim = -1 if dim is None else dim

    def compute_weight(self, layer):
        g = layer._parameters[self.name + "_g"].value
        v = layer._parameters[self.name + "_v"].value
        return _weight_from_gv(g, v, self.dim)

    @staticmethod
    def apply(layer: Layer, name: str, dim):
        for hook in layer._forward_pre_hooks.values():
            if isinstance(hook, WeightNorm) and hook.name == name:
                raise InvalidArgumentError(
                    f"weight_norm already registered on parameter {name!r}")
        w = layer._parameters.get(name)
        if w is None:
            raise InvalidArgumentError(
                f"{type(layer).__name__} has no parameter {name!r}")
        ndim = w.ndim
        if dim is None:
            dim = -1
        if not (-ndim <= dim < ndim):
            raise InvalidArgumentError(
                f"dim must be in [-{ndim}, {ndim}), got {dim}")
        if dim != -1:
            dim = dim % ndim
        fn = WeightNorm(name, dim)

        v = Parameter(w.value, name=(w.name + "_v") if w.name else "",
                      trainable=True)
        g = Parameter(_norm_except_dim(w.value, dim),
                      name=(w.name + "_g") if w.name else "", trainable=True)
        layer.add_parameter(name + "_v", v)
        layer.add_parameter(name + "_g", g)
        # the original becomes a derived, non-trainable cache the hook
        # refreshes each call (optimizers skip trainable=False)
        w.trainable = False
        w.value = fn.compute_weight(layer)
        layer.register_forward_pre_hook(fn)
        return fn

    def remove(self, layer: Layer):
        w = layer._parameters[self.name]
        w.value = self.compute_weight(layer)
        w.trainable = True
        del layer._parameters[self.name + "_g"]
        del layer._parameters[self.name + "_v"]
        for hid, hook in list(layer._forward_pre_hooks.items()):
            if hook is self:
                del layer._forward_pre_hooks[hid]

    def __call__(self, layer, inputs):
        layer._parameters[self.name].value = self.compute_weight(layer)


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0):
    """Apply weight normalization to ``layer.<name>``
    (ref: weight_norm_hook.py:155)."""
    WeightNorm.apply(layer, name, dim)
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight"):
    """Undo ``weight_norm``, folding g·v/||v|| back into one trainable
    parameter (ref: weight_norm_hook.py:203)."""
    for hook in list(layer._forward_pre_hooks.values()):
        if isinstance(hook, WeightNorm) and hook.name == name:
            hook.remove(layer)
            return layer
    raise InvalidArgumentError(
        f"weight_norm of {name!r} not found in {type(layer).__name__}")

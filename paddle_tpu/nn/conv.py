"""Conv layers.

Parity surface: paddle.nn.Conv1D/2D/3D(+Transpose)
(reference: python/paddle/nn/layer/conv.py over operators/conv_op.cc).
"""
from __future__ import annotations

import numpy as np

from . import functional as F
from . import initializer as I
from .layer_base import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose"]


def _ntuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    _ndim = 2
    _transpose = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, output_padding=0, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None, name=None):
        super().__init__()
        n = self._ndim
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, n)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.output_padding = output_padding
        self.data_format = data_format
        if self._transpose:
            # paddle transpose kernel layout: (in_channels, out_channels // g, *k)
            w_shape = (in_channels, out_channels // groups) + self.kernel_size
        else:
            w_shape = (out_channels, in_channels // groups) + self.kernel_size
        fan_in = in_channels // groups * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=I.Normal(0.0, (2.0 / max(fan_in, 1)) ** 0.5))
        if bias_attr is not False:
            self.bias = self.create_parameter((out_channels,), attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def _bias(self):
        return self.bias.value if self.bias is not None else None


class Conv1D(_ConvNd):
    _ndim = 1

    def forward(self, x):
        return F.conv1d(x, self.weight.value, self._bias(), self.stride, self.padding,
                        self.dilation, self.groups, self.data_format or "NCL")


class Conv2D(_ConvNd):
    """Parity: paddle.nn.Conv2D (ref: operators/conv_op.cc; cuDNN variant
    conv_cudnn_op.cu → here one XLA convolution on the MXU)."""

    _ndim = 2

    def forward(self, x):
        return F.conv2d(x, self.weight.value, self._bias(), self.stride, self.padding,
                        self.dilation, self.groups, self.data_format or "NCHW")


class Conv3D(_ConvNd):
    _ndim = 3

    def forward(self, x):
        return F.conv3d(x, self.weight.value, self._bias(), self.stride, self.padding,
                        self.dilation, self.groups, self.data_format or "NCDHW")


class Conv1DTranspose(_ConvNd):
    _ndim = 1
    _transpose = True

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight.value, self._bias(), self.stride,
                                  self.padding, self.output_padding, self.groups,
                                  self.dilation, output_size, self.data_format or "NCL")


class Conv2DTranspose(_ConvNd):
    _ndim = 2
    _transpose = True

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight.value, self._bias(), self.stride,
                                  self.padding, self.output_padding, self.groups,
                                  self.dilation, output_size, self.data_format or "NCHW")


class Conv3DTranspose(_ConvNd):
    _ndim = 3
    _transpose = True

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight.value, self._bias(), self.stride,
                                  self.padding, self.output_padding, self.groups,
                                  self.dilation, output_size, self.data_format or "NCDHW")

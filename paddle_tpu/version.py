__version__ = "0.1.0"
full_version = __version__
major, minor, patch = (int(p) for p in __version__.split("."))
# stamped by setup.py's build_py with the checkout commit (parity:
# cmake/version.cmake → PADDLE_VERSION/commit in fluid/platform/init.cc)
git_commit = "unknown"

"""Flowers-102 dataset (parity: python/paddle/vision/datasets/flowers.py:43).

Reads the standard Oxford 102-flowers artifacts: ``102flowers.tgz`` (jpg
archive), ``imagelabels.mat``, ``setid.mat``.  No network egress: missing
files raise with instructions.
"""
from __future__ import annotations

import io
import os
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Flowers"]

from ...io.dataset import DEFAULT_DATA_ROOT as _DEFAULT_ROOT

# reference flowers.py:38 MODE_FLAG_MAP: the setid.mat split keys
_MODE_FLAG = {"train": "trnid", "valid": "valid", "test": "tstid"}


class Flowers(Dataset):
    """Samples are ``(image, label)``; label int64 in [0, 102) (the .mat
    labels are 1-based — shifted down, unlike the reference which keeps
    them raw)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        import scipy.io as scio

        if mode not in _MODE_FLAG:
            raise ValueError(f"mode must be one of {sorted(_MODE_FLAG)}")
        if backend not in (None, "pil", "cv2"):
            raise ValueError(
                f"backend must be 'pil' or 'cv2', got {backend!r}")
        self.mode = mode
        self.transform = transform
        self.backend = backend or "cv2"
        data_file = data_file or os.path.join(_DEFAULT_ROOT,
                                              "102flowers.tgz")
        label_file = label_file or os.path.join(_DEFAULT_ROOT,
                                                "imagelabels.mat")
        setid_file = setid_file or os.path.join(_DEFAULT_ROOT, "setid.mat")
        for p in (data_file, label_file, setid_file):
            if not os.path.exists(p):
                raise FileNotFoundError(
                    f"{p} not found and this environment has no network "
                    f"egress: place the Oxford 102-flowers artifacts there "
                    f"(or pass data_file/label_file/setid_file)")
        self.data_file = data_file
        self._tar = None  # opened lazily, per process (tar handles don't
        #                   pickle — DataLoader workers re-open their own)
        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[_MODE_FLAG[mode]][0]

    def _archive(self):
        if self._tar is None:
            self._tar = tarfile.open(self.data_file, "r:*")
        return self._tar

    def __getstate__(self):
        return {**self.__dict__, "_tar": None}

    def __getitem__(self, idx):
        from PIL import Image

        index = int(self.indexes[idx])
        label = np.int64(self.labels[index - 1] - 1)
        blob = self._archive().extractfile(
            "jpg/image_%05d.jpg" % index).read()
        img = Image.open(io.BytesIO(blob))
        if self.backend == "cv2":
            img = np.asarray(img)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.indexes)

"""Cifar10/Cifar100 datasets (parity: python/paddle/vision/datasets/cifar.py).

Reads the standard python-version tar.gz archives (pickled batches).  No
network egress: missing files raise with instructions.
"""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Cifar10", "Cifar100"]

from ...io.dataset import DEFAULT_DATA_ROOT as _DEFAULT_ROOT


class Cifar10(Dataset):
    """Samples are ``(image, label)`` — image float32 [3, 32, 32], label
    int64."""

    NAME = "cifar-10-python.tar.gz"
    _MEMBER_PREFIX = "cifar-10-batches-py"
    _LABEL_KEY = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be 'train' or 'test', got {mode!r}")
        self.mode = mode
        self.transform = transform
        data_file = data_file or os.path.join(_DEFAULT_ROOT, self.NAME)
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{data_file} not found and this environment has no network "
                f"egress: place the standard python-version archive there "
                f"(or pass data_file)")
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tar:
            for member in sorted(tar.getmembers(), key=lambda m: m.name):
                name = os.path.basename(member.name)
                keep = (name.startswith("data_batch") or name == "train"
                        if mode == "train"
                        else name.startswith("test_batch") or name == "test")
                if not keep:
                    continue
                batch = pickle.load(tar.extractfile(member), encoding="bytes")
                images.append(np.asarray(batch[b"data"], np.uint8))
                labels.extend(batch[self._LABEL_KEY])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)
        label = np.asarray(self.labels[idx], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NAME = "cifar-100-python.tar.gz"
    _MEMBER_PREFIX = "cifar-100-python"
    _LABEL_KEY = b"fine_labels"

"""MNIST / FashionMNIST datasets.

Parity surface: python/paddle/vision/datasets/mnist.py:30 (MNIST(image_path,
label_path, mode, transform, download)).  Reads the standard IDX
gzip files.  This environment has no network egress, so ``download=True``
with no local copy raises with instructions instead of fetching.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST"]

from ...io.dataset import DEFAULT_DATA_ROOT as _DEFAULT_ROOT


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{path}: bad IDX image magic {magic}")
        data = np.frombuffer(f.read(n * rows * cols), np.uint8)
    return data.reshape(n, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{path}: bad IDX label magic {magic}")
        return np.frombuffer(f.read(n), np.uint8)


class MNIST(Dataset):
    """Each sample is ``(image, label)`` — image float32 [1, 28, 28] scaled
    to [-1, 1] when ``backend='cv2'``-style raw, or whatever ``transform``
    returns; label int64 scalar (paddle parity)."""

    NAME = "mnist"
    _FILES = {
        "train": ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
        "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
    }

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be 'train' or 'test', got {mode!r}")
        self.mode = mode
        self.transform = transform
        self.backend = backend or "numpy"
        root = os.path.join(_DEFAULT_ROOT, self.NAME)
        img_file, lbl_file = self._FILES[mode]
        image_path = image_path or os.path.join(root, img_file)
        label_path = label_path or os.path.join(root, lbl_file)
        for p in (image_path, label_path):
            if not os.path.exists(p):
                raise FileNotFoundError(
                    f"{self.NAME} file {p} not found and this environment "
                    f"has no network egress: place the standard IDX .gz "
                    f"files there (or pass image_path/label_path)")
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :]  # [1,28,28]
        label = np.asarray(self.labels[idx], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    """Same IDX format, different files (parity:
    python/paddle/vision/datasets/__init__.py FashionMNIST)."""

    NAME = "fashion-mnist"

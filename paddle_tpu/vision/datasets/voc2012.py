"""VOC2012 segmentation dataset (parity:
python/paddle/vision/datasets/voc2012.py:41).

Reads the standard ``VOCtrainval_11-May-2012.tar`` layout: image-set
lists under ImageSets/Segmentation, jpgs under JPEGImages, png label
masks under SegmentationClass.  No network egress: a missing archive
raises with instructions.
"""
from __future__ import annotations

import io
import os
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["VOC2012"]

from ...io.dataset import DEFAULT_DATA_ROOT as _DEFAULT_ROOT

_SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
_DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
_LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
# reference voc2012.py:38 — yes, 'test' maps to the 'train' list there too
_MODE_FLAG = {"train": "trainval", "test": "train", "valid": "val"}


class VOC2012(Dataset):
    """Samples are ``(image, label_mask)`` numpy arrays (HWC uint8 /
    HW uint8)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if mode not in _MODE_FLAG:
            raise ValueError(f"mode must be one of {sorted(_MODE_FLAG)}")
        if backend not in (None, "pil", "cv2"):
            raise ValueError(
                f"backend must be 'pil' or 'cv2', got {backend!r}")
        self.mode = mode
        self.transform = transform
        self.backend = backend or "cv2"
        data_file = data_file or os.path.join(
            _DEFAULT_ROOT, "VOCtrainval_11-May-2012.tar")
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{data_file} not found and this environment has no network "
                f"egress: place the VOCtrainval archive there (or pass "
                f"data_file)")
        self.data_file = data_file
        self._tar = None  # opened lazily, per process (tar handles don't
        #                   pickle — DataLoader workers re-open their own)
        listing = self._archive().extractfile(
            _SET_FILE.format(_MODE_FLAG[mode])).read()
        self.names = [l.strip() for l in listing.decode().splitlines()
                      if l.strip()]

    def _archive(self):
        if self._tar is None:
            self._tar = tarfile.open(self.data_file, "r:*")
        return self._tar

    def __getstate__(self):
        return {**self.__dict__, "_tar": None}

    def _read(self, path):
        from PIL import Image

        blob = self._archive().extractfile(path).read()
        img = Image.open(io.BytesIO(blob))
        return np.asarray(img) if self.backend == "cv2" else img

    def __getitem__(self, idx):
        name = self.names[idx]
        img = self._read(_DATA_FILE.format(name))
        label = self._read(_LABEL_FILE.format(name))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.names)

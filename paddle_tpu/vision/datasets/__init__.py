"""Vision datasets (parity: python/paddle/vision/datasets/__init__.py)."""
from .cifar import Cifar10, Cifar100  # noqa: F401
from .folder import (  # noqa: F401
    DatasetFolder,
    ImageFolder,
    cv2_loader,
    default_loader,
    pil_loader,
)
from .flowers import Flowers  # noqa: F401
from .voc2012 import VOC2012  # noqa: F401
from .mnist import MNIST, FashionMNIST  # noqa: F401

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder", "Flowers", "VOC2012", "pil_loader", "cv2_loader",
           "default_loader"]

"""DatasetFolder / ImageFolder (parity:
python/paddle/vision/datasets/folder.py).

Directory-per-class layout → (sample, class_index); flat directory of
images → samples only.
"""
from __future__ import annotations

import os

import numpy as np

from ...io import Dataset
from ..image import image_load

__all__ = ["DatasetFolder", "ImageFolder", "pil_loader", "cv2_loader", "default_loader"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def has_valid_extension(filename, extensions=IMG_EXTENSIONS):
    return filename.lower().endswith(tuple(extensions))


def pil_loader(path):
    """Reference folder.py pil_loader — a PIL RGB image."""
    from PIL import Image

    with open(path, "rb") as f:
        return Image.open(f).convert("RGB")


def cv2_loader(path):
    """Reference folder.py cv2_loader — cv2.imread, i.e. an HWC **BGR**
    ndarray (matching image_load's cv2 backend; pil_loader is RGB)."""
    import cv2

    return cv2.imread(path)


def default_loader(path):
    """Reference folder.py default_loader: backend-dispatched read."""
    img = image_load(path)
    if hasattr(img, "convert"):
        img = img.convert("RGB")
    return np.asarray(img)


_default_loader = default_loader


def make_dataset(directory, class_to_idx, extensions=None, is_valid_file=None):
    instances = []
    if is_valid_file is None:
        def is_valid_file(p):
            return has_valid_extension(p, extensions or IMG_EXTENSIONS)
    for target_class in sorted(class_to_idx):
        class_index = class_to_idx[target_class]
        target_dir = os.path.join(directory, target_class)
        if not os.path.isdir(target_dir):
            continue
        for root, _, fnames in sorted(os.walk(target_dir, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root, fname)
                if is_valid_file(path):
                    instances.append((path, class_index))
    return instances


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        classes, class_to_idx = self._find_classes(root)
        samples = make_dataset(root, class_to_idx, extensions, is_valid_file)
        if not samples:
            raise RuntimeError(f"found no valid files under {root}")
        self.classes = classes
        self.class_to_idx = class_to_idx
        self.samples = samples
        self.targets = [s[1] for s in samples]

    @staticmethod
    def _find_classes(directory):
        classes = sorted(e.name for e in os.scandir(directory) if e.is_dir())
        if not classes:
            raise RuntimeError(f"no class folders found in {directory}")
        return classes, {c: i for i, c in enumerate(classes)}

    def __getitem__(self, index):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, np.asarray(target, np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat folder of images — samples only, no labels."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        if is_valid_file is None:
            def is_valid_file(p):
                return has_valid_extension(p, extensions or IMG_EXTENSIONS)
        samples = []
        for path_root, _, fnames in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(path_root, fname)
                if is_valid_file(path):
                    samples.append(path)
        if not samples:
            raise RuntimeError(f"found no valid files under {root}")
        self.samples = samples

    def __getitem__(self, index):
        sample = self.loader(self.samples[index])
        if self.transform is not None:
            sample = self.transform(sample)
        return (sample,)

    def __len__(self):
        return len(self.samples)

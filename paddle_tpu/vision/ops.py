"""paddle.vision.ops — vision operators.

Forward-compat module (2.0+ moves roi/nms/yolo ops here; at the
reference version they live in fluid.layers).  All implementations are
in nn/functional/detection.py.
"""
from ..nn.functional.detection import (  # noqa: F401
    box_coder, nms, multiclass_nms, prior_box, roi_align, roi_pool,
    sigmoid_focal_loss, yolo_box,
)
from ..nn.functional.deform_conv import deform_conv2d  # noqa: F401

__all__ = ["box_coder", "nms", "multiclass_nms", "prior_box", "roi_align",
           "roi_pool", "sigmoid_focal_loss", "yolo_box", "deform_conv2d"]

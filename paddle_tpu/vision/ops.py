"""paddle.vision.ops — vision operators.

Forward-compat module (2.0+ moves roi/nms/yolo ops here; at the
reference version they live in fluid.layers).  All implementations are
in nn/functional/detection.py.
"""
from ..nn.functional.detection import (  # noqa: F401
    box_coder, nms, multiclass_nms, prior_box, roi_align, roi_pool,
    sigmoid_focal_loss, yolo_box,
)
from ..nn.functional.deform_conv import deform_conv2d  # noqa: F401

__all__ = ["box_coder", "nms", "multiclass_nms", "prior_box", "roi_align",
           "roi_pool", "sigmoid_focal_loss", "yolo_box", "deform_conv2d"]


_multi_box_head_cls = None


def _build_multi_box_head():
    """Build the MultiBoxHead Layer class once (lazy: vision.ops must not
    import paddle_tpu.nn at module load)."""
    global _multi_box_head_cls
    if _multi_box_head_cls is None:
        from .. import nn

        class MultiBoxHead(nn.Layer):
            """SSD multi-box head (ref: fluid/layers/detection.py:2102
            multi_box_head) as an eager Layer: per feature map, a conv
            pair produces location offsets and class confidences while
            prior boxes generate on the same grid; everything
            concatenates across maps.  The 1.x builder created its conv
            parameters inside the op graph; here they live in the Layer
            (``in_channels`` declares each feature map's channels).

            Call with (inputs: list of [N, Ci, Hi, Wi], image) →
            (mbox_locs [N, total, 4], mbox_confs [N, total, classes],
            boxes [total, 4], variances [total, 4])."""

            def __init__(self, in_channels, base_size, num_classes,
                         aspect_ratios, min_ratio=None, max_ratio=None,
                         min_sizes=None, max_sizes=None, steps=None,
                         step_w=None, step_h=None, offset=0.5,
                         variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                         clip=False, kernel_size=1, pad=0, stride=1,
                         min_max_aspect_ratios_order=False):
                super().__init__()
                from ..framework.errors import InvalidArgumentError

                num_layer = len(in_channels)
                if num_layer <= 2:
                    if min_sizes is None or max_sizes is None:
                        raise InvalidArgumentError(
                            "<=2 inputs need explicit min_sizes/max_sizes")
                elif min_sizes is None and max_sizes is None:
                    import math as _m

                    min_sizes, max_sizes = [], []
                    step = int(_m.floor((max_ratio - min_ratio)
                                        / (num_layer - 2)))
                    for ratio in range(min_ratio, max_ratio + 1, step):
                        min_sizes.append(base_size * ratio / 100.0)
                        max_sizes.append(base_size * (ratio + step) / 100.0)
                    min_sizes = [base_size * 0.10] + min_sizes
                    max_sizes = [base_size * 0.20] + max_sizes
                if len(aspect_ratios) != num_layer:
                    raise InvalidArgumentError(
                        "aspect_ratios must match the number of inputs")
                if steps is not None:
                    step_w = step_h = steps
                for nm, val in (("steps", steps), ("step_w", step_w),
                                ("step_h", step_h)):
                    if val is not None and (
                            not isinstance(val, (list, tuple))
                            or len(val) != num_layer):
                        raise InvalidArgumentError(
                            f"{nm} must be a list/tuple with one entry per "
                            f"input ({num_layer}), got {val!r}")
                self._cfg = dict(
                    min_sizes=min_sizes, max_sizes=max_sizes,
                    aspect_ratios=aspect_ratios, variance=tuple(variance),
                    flip=flip, clip=clip, offset=offset,
                    step_w=step_w, step_h=step_h,
                    mmaro=min_max_aspect_ratios_order)
                self.num_classes = int(num_classes)
                self.loc_convs = nn.LayerList()
                self.conf_convs = nn.LayerList()
                for i, cin in enumerate(in_channels):
                    npb = self._num_priors(i)
                    self.loc_convs.append(nn.Conv2D(
                        cin, npb * 4, kernel_size, stride=stride,
                        padding=pad))
                    self.conf_convs.append(nn.Conv2D(
                        cin, npb * self.num_classes, kernel_size,
                        stride=stride, padding=pad))

            def _num_priors(self, i):
                # EXACTLY prior_box's aspect-ratio dedup (detection.py
                # prior_box): ars = [1] + new ratios (+ flips), K =
                # len(min)*len(ars) + min(len(min), len(max))
                ar = self._cfg["aspect_ratios"][i]
                ar = list(ar) if isinstance(ar, (list, tuple)) else [ar]
                ms = self._cfg["min_sizes"][i]
                ms = list(ms) if isinstance(ms, (list, tuple)) else [ms]
                mx = self._cfg["max_sizes"][i]
                mx = list(mx) if isinstance(mx, (list, tuple)) else [mx]
                ars = [1.0]
                for a in ar:
                    a = float(a)
                    if not any(abs(a - e) < 1e-6 for e in ars):
                        ars.append(a)
                        if self._cfg["flip"]:
                            ars.append(1.0 / a)
                return len(ms) * len(ars) + min(len(ms), len(mx))

            def forward(self, inputs, image):
                import jax.numpy as jnp

                from ..nn import functional as F

                cfg = self._cfg
                locs, confs, boxes, vars_ = [], [], [], []
                for i, feat in enumerate(inputs):
                    ms = cfg["min_sizes"][i]
                    ms = list(ms) if isinstance(ms, (list, tuple)) else [ms]
                    mx = cfg["max_sizes"][i]
                    mx = list(mx) if isinstance(mx, (list, tuple)) else [mx]
                    ar = cfg["aspect_ratios"][i]
                    ar = list(ar) if isinstance(ar, (list, tuple)) else [ar]
                    step = (cfg["step_w"][i] if cfg["step_w"] else 0.0,
                            cfg["step_h"][i] if cfg["step_h"] else 0.0)
                    box, var = F.prior_box(
                        feat, image, ms, mx, ar, cfg["variance"],
                        cfg["flip"], cfg["clip"], step, cfg["offset"],
                        min_max_aspect_ratios_order=cfg["mmaro"])
                    boxes.append(jnp.reshape(box, (-1, 4)))
                    vars_.append(jnp.reshape(var, (-1, 4)))
                    loc = self.loc_convs[i](feat)        # [N, P*4, H, W]
                    N = loc.shape[0]
                    loc = jnp.transpose(jnp.asarray(loc), (0, 2, 3, 1))
                    locs.append(loc.reshape(N, -1, 4))
                    conf = self.conf_convs[i](feat)
                    conf = jnp.transpose(jnp.asarray(conf), (0, 2, 3, 1))
                    confs.append(conf.reshape(N, -1, self.num_classes))
                return (jnp.concatenate(locs, 1), jnp.concatenate(confs, 1),
                        jnp.concatenate(boxes, 0), jnp.concatenate(vars_, 0))

        MultiBoxHead.__module__ = __name__
        MultiBoxHead.__qualname__ = "MultiBoxHead"
        _multi_box_head_cls = MultiBoxHead
    return _multi_box_head_cls


def __getattr__(name):
    if name == "MultiBoxHead":
        return _build_multi_box_head()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__.append("MultiBoxHead")

"""Functional image transforms (parity surface:
python/paddle/vision/transforms/functional.py).

Host-side preprocessing: these run in DataLoader workers on numpy arrays
(HWC) or PIL Images — never on device.  The device path starts after
batching (``to_tensor`` output feeds the double-buffered device_put stage,
io/dataloader.py), so keeping these in numpy/PIL is the TPU-native split:
cheap scalar image math on host CPU, dense batched math on TPU.
"""
from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "to_tensor", "resize", "pad", "crop", "center_crop", "hflip", "vflip",
    "normalize", "transpose", "adjust_brightness", "adjust_contrast",
    "adjust_saturation", "adjust_hue", "rotate", "to_grayscale",
]


def _is_pil(img):
    try:
        from PIL import Image

        return isinstance(img, Image.Image)
    except ImportError:  # pragma: no cover
        return False


def _to_numpy(img):
    """PIL.Image | ndarray → HWC uint8/float ndarray."""
    if _is_pil(img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def to_tensor(pic, data_format="CHW"):
    """Image → float32 array in [0, 1] (CHW by default, matching the
    reference's ToTensor semantics)."""
    arr = _to_numpy(pic)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    else:
        arr = arr.astype(np.float32)
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    elif data_format != "HWC":
        raise ValueError(f"data_format must be CHW or HWC, got {data_format}")
    return arr


def _pil_interp(interpolation):
    from PIL import Image

    return {
        "nearest": Image.NEAREST,
        "bilinear": Image.BILINEAR,
        "bicubic": Image.BICUBIC,
        "lanczos": Image.LANCZOS,
        "box": Image.BOX,
        "hamming": Image.HAMMING,
    }[interpolation]


def resize(img, size, interpolation="bilinear"):
    """size: int (short side) or (h, w)."""
    from PIL import Image

    pil = img if _is_pil(img) else Image.fromarray(np.squeeze(_to_numpy(img)))
    w, h = pil.size
    if isinstance(size, int):
        if (w <= h and w == size) or (h <= w and h == size):
            out = pil
        elif w < h:
            out = pil.resize((size, int(size * h / w)), _pil_interp(interpolation))
        else:
            out = pil.resize((int(size * w / h), size), _pil_interp(interpolation))
    else:
        out = pil.resize((size[1], size[0]), _pil_interp(interpolation))
    return out if _is_pil(img) else _to_numpy(out)


def pad(img, padding, fill=0, padding_mode="constant"):
    """padding: int | (pad_lr, pad_tb) | (left, top, right, bottom)."""
    arr = _to_numpy(img)
    if isinstance(padding, numbers.Number):
        left = top = right = bottom = int(padding)
    elif len(padding) == 2:
        left = right = int(padding[0])
        top = bottom = int(padding[1])
    else:
        left, top, right, bottom = (int(p) for p in padding)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    out = np.pad(arr, ((top, bottom), (left, right), (0, 0)), mode=mode, **kw)
    return _back(img, out)


def _back(orig, arr):
    """Return in the caller's type (PIL in → PIL out)."""
    if _is_pil(orig):
        from PIL import Image

        return Image.fromarray(np.squeeze(arr))
    return arr


def crop(img, top, left, height, width):
    arr = _to_numpy(img)
    return _back(img, arr[top:top + height, left:left + width])


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = _to_numpy(img)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(img, top, left, th, tw)


def hflip(img):
    return _back(img, _to_numpy(img)[:, ::-1])


def vflip(img):
    return _back(img, _to_numpy(img)[::-1])


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    if to_rgb:
        ch_axis = 0 if data_format == "CHW" else -1
        arr = np.flip(arr, axis=ch_axis)
    return (arr - mean.reshape(shape)) / std.reshape(shape)


def transpose(img, order=(2, 0, 1)):
    return _to_numpy(img).transpose(order)


def adjust_brightness(img, brightness_factor):
    arr = _to_numpy(img).astype(np.float32)
    out = np.clip(arr * brightness_factor, 0, 255)
    return _back(img, out.astype(np.uint8) if _to_numpy(img).dtype == np.uint8 else out)


def adjust_contrast(img, contrast_factor):
    arr = _to_numpy(img).astype(np.float32)
    gray_mean = _rgb_to_gray(arr).mean()
    out = np.clip(gray_mean + contrast_factor * (arr - gray_mean), 0, 255)
    return _back(img, out.astype(np.uint8) if _to_numpy(img).dtype == np.uint8 else out)


def _rgb_to_gray(arr):
    if arr.shape[-1] == 1:
        return arr[..., 0]
    return 0.299 * arr[..., 0] + 0.587 * arr[..., 1] + 0.114 * arr[..., 2]


def adjust_saturation(img, saturation_factor):
    arr = _to_numpy(img).astype(np.float32)
    gray = _rgb_to_gray(arr)[..., None]
    out = np.clip(gray + saturation_factor * (arr - gray), 0, 255)
    return _back(img, out.astype(np.uint8) if _to_numpy(img).dtype == np.uint8 else out)


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    from PIL import Image

    pil = img if _is_pil(img) else Image.fromarray(np.squeeze(_to_numpy(img)))
    if pil.mode in ("L", "1", "I", "F"):
        out = pil
    else:
        h, s, v = pil.convert("HSV").split()
        h_arr = np.asarray(h, np.uint8)
        h_arr = (h_arr.astype(np.int16) + int(hue_factor * 255)).astype(np.uint8)
        out = Image.merge("HSV", (Image.fromarray(h_arr, "L"), s, v)).convert(pil.mode)
    return out if _is_pil(img) else _to_numpy(out)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    from PIL import Image

    pil = img if _is_pil(img) else Image.fromarray(np.squeeze(_to_numpy(img)))
    interp = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
              "bicubic": Image.BICUBIC}[interpolation]
    out = pil.rotate(angle, interp, expand, center, fillcolor=fill)
    return out if _is_pil(img) else _to_numpy(out)


def to_grayscale(img, num_output_channels=1):
    arr = _to_numpy(img).astype(np.float32)
    gray = _rgb_to_gray(arr)[..., None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=-1)
    elif num_output_channels != 1:
        raise ValueError("num_output_channels must be 1 or 3")
    out = gray.astype(np.uint8) if _to_numpy(img).dtype == np.uint8 else gray
    return _back(img, out)

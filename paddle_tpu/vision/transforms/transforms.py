"""Transform classes (parity surface:
python/paddle/vision/transforms/transforms.py:83-1170).

Each transform is a callable object; ``Compose`` chains them.  Like the
reference's ``BaseTransform``, multi-field samples are supported through
``keys`` — fields named 'image' get the image op, others pass through.
Randomness uses module-level numpy RNG (host side; device RNG is the
framework Generator).
"""
from __future__ import annotations

import numbers
import random as _pyrandom

import numpy as np

from . import functional as F

__all__ = [
    "Compose", "BaseTransform", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip", "Normalize",
    "Transpose", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "ColorJitter", "RandomCrop",
    "Pad", "RandomRotation", "Grayscale",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data

    def __repr__(self):
        inner = ", ".join(repr(t) for t in self.transforms)
        return f"Compose([{inner}])"


class BaseTransform:
    """Apply `_apply_image` to the image field(s) of a sample.

    ``keys``: like the reference (transforms.py:134), a tuple naming each
    element of a tuple-sample ('image', 'coords', 'boxes', 'mask', or None
    to pass through).  A bare (non-tuple) input is treated as one image.
    """

    def __init__(self, keys=None):
        self.keys = keys if keys is not None else ("image",)
        self.params = None

    def _get_params(self, inputs):
        return None

    def __call__(self, inputs):
        bare = not isinstance(inputs, (tuple, list))
        sample = (inputs,) if bare else tuple(inputs)
        self.params = self._get_params(sample)
        outputs = []
        for key, data in zip(self.keys, sample):
            if key is None:
                outputs.append(data)
            else:
                apply = getattr(self, f"_apply_{key}", None)
                outputs.append(apply(data) if apply is not None else data)
        outputs.extend(sample[len(self.keys):])
        if bare:
            return outputs[0]
        return tuple(outputs)

    def _apply_image(self, image):
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _sample_crop(self, h, w):
        area = h * w
        for _ in range(10):
            target_area = area * _pyrandom.uniform(*self.scale)
            log_ratio = (np.log(self.ratio[0]), np.log(self.ratio[1]))
            aspect = np.exp(_pyrandom.uniform(*log_ratio))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = _pyrandom.randint(0, h - ch)
                left = _pyrandom.randint(0, w - cw)
                return top, left, ch, cw
        # fallback: center crop at the clamped aspect
        in_ratio = w / h
        if in_ratio < self.ratio[0]:
            cw, ch = w, int(round(w / self.ratio[0]))
        elif in_ratio > self.ratio[1]:
            ch, cw = h, int(round(h * self.ratio[1]))
        else:
            cw, ch = w, h
        return (h - ch) // 2, (w - cw) // 2, ch, cw

    def _apply_image(self, img):
        arr = np.asarray(img) if not hasattr(img, "size") else None
        if arr is not None:
            h, w = arr.shape[:2]
        else:
            w, h = img.size
        top, left, ch, cw = self._sample_crop(h, w)
        out = F.crop(img, top, left, ch, cw)
        return F.resize(out, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if _pyrandom.random() < self.prob:
            return F.hflip(img)
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if _pyrandom.random() < self.prob:
            return F.vflip(img)
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format
        self.to_rgb = to_rgb

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format,
                           self.to_rgb)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return F.transpose(img, self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = _check_jitter(value, "brightness")

    def _apply_image(self, img):
        if self.value is None:
            return img
        return F.adjust_brightness(img, _pyrandom.uniform(*self.value))


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = _check_jitter(value, "contrast")

    def _apply_image(self, img):
        if self.value is None:
            return img
        return F.adjust_contrast(img, _pyrandom.uniform(*self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = _check_jitter(value, "saturation")

    def _apply_image(self, img):
        if self.value is None:
            return img
        return F.adjust_saturation(img, _pyrandom.uniform(*self.value))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = _check_jitter(value, "hue", center=0,
                                   bound=(-0.5, 0.5))

    def _apply_image(self, img):
        if self.value is None:
            return img
        return F.adjust_hue(img, _pyrandom.uniform(*self.value))


def _check_jitter(value, name, center=1, bound=(0, float("inf"))):
    if isinstance(value, numbers.Number):
        if value < 0:
            raise ValueError(f"{name} value must be non-negative")
        value = [max(center - value, bound[0]), min(center + value, bound[1])]
    elif len(value) != 2:
        raise ValueError(f"{name} must be a number or a 2-tuple")
    if value[0] == value[1] == center:
        return None
    return tuple(float(v) for v in value)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self._ops = [
            BrightnessTransform(brightness), ContrastTransform(contrast),
            SaturationTransform(saturation), HueTransform(hue),
        ]

    def _apply_image(self, img):
        ops = list(self._ops)
        _pyrandom.shuffle(ops)
        for op in ops:
            img = op._apply_image(img)
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        arr = F._to_numpy(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and w < tw:
            img = F.pad(img, (tw - w, 0), self.fill, self.padding_mode)
        if self.pad_if_needed and h < th:
            img = F.pad(img, (0, th - h), self.fill, self.padding_mode)
        arr = F._to_numpy(img)
        h, w = arr.shape[:2]
        if h == th and w == tw:
            return img
        top = _pyrandom.randint(0, h - th)
        left = _pyrandom.randint(0, w - tw)
        return F.crop(img, top, left, th, tw)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            degrees = (-degrees, degrees)
        self.degrees = tuple(float(d) for d in degrees)
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = _pyrandom.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)

"""Image backend selection (parity: python/paddle/vision/image.py).

Backends: 'pil' (default) and 'cv2'.  ``image_load`` returns the backend's
native image object; datasets convert to numpy HWC before batching (the
device only ever sees dense numpy/jax arrays).
"""
from __future__ import annotations

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_image_backend = "pil"


def set_image_backend(backend: str) -> None:
    if backend not in ("pil", "cv2"):
        raise ValueError(f"backend must be 'pil' or 'cv2', got {backend!r}")
    global _image_backend
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path, backend=None):
    backend = backend or _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"backend must be 'pil' or 'cv2', got {backend!r}")
    if backend == "pil":
        from PIL import Image

        return Image.open(path)
    import cv2

    return cv2.imread(str(path))

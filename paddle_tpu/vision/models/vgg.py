"""VGG family (parity: python/paddle/vision/models/vgg.py:34-199).
``data_format="NHWC"`` runs the TPU-preferred layout with the same
state_dict (the classifier sees NCHW-ordered features via one transpose
before flatten)."""
from __future__ import annotations

import jax.numpy as jnp

from ... import nn

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19"]


class VGG(nn.Layer):
    """``features`` is the conv trunk built by :func:`make_layers`."""

    def __init__(self, features, num_classes=1000, data_format="NCHW"):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.data_format = data_format
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096),
                nn.ReLU(),
                nn.Dropout(),
                nn.Linear(4096, 4096),
                nn.ReLU(),
                nn.Dropout(),
                nn.Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            if self.data_format == "NHWC":
                # classifier weights are NCHW-flat: one cheap transpose
                # keeps state_dicts layout-portable
                x = jnp.transpose(jnp.asarray(x), (0, 3, 1, 2))
            x = x.reshape(x.shape[0], -1)
            x = self.classifier(x)
        return x


def make_layers(cfg, batch_norm=False, data_format="NCHW"):
    layers = []
    in_channels = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(kernel_size=2, stride=2,
                                       data_format=data_format))
        else:
            layers.append(nn.Conv2D(in_channels, v, 3, padding=1,
                                    data_format=data_format))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v, data_format=data_format))
            layers.append(nn.ReLU())
            in_channels = v
    return nn.Sequential(*layers)


_cfgs = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def _vgg(arch, cfg, batch_norm, pretrained, **kwargs):
    df = kwargs.get("data_format", "NCHW")
    model = VGG(make_layers(_cfgs[cfg], batch_norm=batch_norm,
                            data_format=df), **kwargs)
    if pretrained:
        from ...framework import serialization

        if not isinstance(pretrained, str):
            raise ValueError(
                "no pretrained-weight download in this environment: pass a "
                "local .pdparams path as `pretrained`")
        model.set_state_dict(serialization.load(pretrained))
    return model


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg11", "A", batch_norm, pretrained, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg13", "B", batch_norm, pretrained, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg16", "D", batch_norm, pretrained, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg19", "E", batch_norm, pretrained, **kwargs)

"""LeNet (parity: python/paddle/vision/models/lenet.py:21).

The BASELINE config-1 smoke model: MNIST digits, 1×28×28 input.
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import nn

__all__ = ["LeNet"]


class LeNet(nn.Layer):
    def __init__(self, num_classes=10, data_format="NCHW"):
        super().__init__()
        self.num_classes = num_classes
        self.data_format = data_format
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1,
                      data_format=data_format),
            nn.ReLU(),
            nn.MaxPool2D(2, 2, data_format=data_format),
            nn.Conv2D(6, 16, 5, stride=1, padding=0,
                      data_format=data_format),
            nn.ReLU(),
            nn.MaxPool2D(2, 2, data_format=data_format),
        )
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120),
                nn.Linear(120, 84),
                nn.Linear(84, num_classes),
            )

    def forward(self, inputs):
        x = self.features(inputs)
        if self.num_classes > 0:
            if self.data_format == "NHWC":
                x = jnp.transpose(jnp.asarray(x), (0, 3, 1, 2))
            x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x

"""ResNet family.

Capability parity: python/paddle/vision/models/resnet.py (ResNet:151,
resnet18:272 … resnet152:352 in the reference).  TPU-native notes: each
residual block is a handful of XLA convolutions the compiler fuses with the
following BN+ReLU; the whole network jit-compiles to one executable.  BN
running stats are Buffers so the train step stays purely functional
(`functional_call(..., return_buffers=True)`).

No pretrained-weight download: this environment has no egress; pass a local
state-dict path via ``pretrained`` instead (or leave False).
"""
from __future__ import annotations

import functools

from ... import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152"]


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        if norm_layer is None:
            norm_layer = functools.partial(nn.BatchNorm2D,
                                           data_format=data_format)
        if groups != 1 or base_width != 64:
            raise ValueError("BasicBlock only supports groups=1, base_width=64")
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, data_format=data_format)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=data_format)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        if norm_layer is None:
            norm_layer = functools.partial(nn.BatchNorm2D,
                                           data_format=data_format)
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=data_format)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, stride=stride, padding=dilation,
                               groups=groups, dilation=dilation, bias_attr=False,
                               data_format=data_format)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1, bias_attr=False,
                               data_format=data_format)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def _fused_tail(self, out, identity):
        """conv3 (1x1) → train-mode bn3 → +identity → relu through the
        fused Pallas pair (``conv1x1_bn_stats`` + ``bn_apply_relu``): two
        passes over the conv output instead of XLA's three, with the
        residual read and ReLU pinned into the second.  Eligible only in
        training (eval BN needs no batch stats — XLA already folds it),
        NHWC layout, and under ``fused_epilogues_eligible`` (real TPU,
        lane-aligned channels, unsharded mesh).  Returns None when
        ineligible — the caller's plain path is the reference."""
        cv, bn = self.conv3, self.bn3
        if not (self.training and cv.data_format == "NHWC"
                and cv.kernel_size == (1, 1) and cv.stride == 1
                and cv.groups == 1 and cv.bias is None
                and bn.weight is not None and bn.bias is not None
                and not bn.use_global_stats):
            return None
        from ...ops.autotune import fused_epilogues_eligible

        cout = cv.out_channels
        if not fused_epilogues_eligible(cout):
            return None
        import jax.numpy as jnp

        from ...ops.fused_conv1x1_bn import conv1x1_bn_relu

        x = jnp.asarray(out)
        n, h, w_, cin = x.shape
        w = jnp.asarray(cv.weight.value).reshape(cout, cin).T  # [Cin, Cout]
        y, nrm, nrv = conv1x1_bn_relu(
            x.reshape(-1, cin), w,
            jnp.asarray(bn.weight.value), jnp.asarray(bn.bias.value),
            epsilon=bn.epsilon, momentum=bn.momentum,
            residual=jnp.asarray(identity).reshape(-1, cout),
            running_mean=bn._mean.value, running_var=bn._variance.value,
            fused_epilogue=True)
        bn._mean.value = nrm
        bn._variance.value = nrv
        return y.reshape(n, h, w_, cout)

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        if self.downsample is not None:
            identity = self.downsample(x)
        fused = self._fused_tail(out, identity)
        if fused is not None:
            return fused
        out = self.bn3(self.conv3(out))
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """ResNet model from "Deep Residual Learning for Image Recognition".

    Args match the reference surface (resnet.py:174): ``block`` class,
    ``depth`` in {18, 34, 50, 101, 152}, ``num_classes`` (≤0 disables the
    fc head), ``with_pool``.
    """

    _layer_cfg = {
        18: [2, 2, 2, 2],
        34: [3, 4, 6, 3],
        50: [3, 4, 6, 3],
        101: [3, 4, 23, 3],
        152: [3, 8, 36, 3],
    }

    def __init__(self, block, depth, num_classes=1000, with_pool=True,
                 data_format="NCHW", stem_space_to_depth=False):
        super().__init__()
        layers = self._layer_cfg[depth]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.data_format = data_format
        # TPU stem optimization: rewrite the 7x7/s2 conv on 3 channels (MXU
        # utilization-bound: C=3 of 128 lanes) as the EQUIVALENT 4x4/s1
        # conv on the 2x2 space-to-depth input (12 channels) — same math,
        # same parameters (weights re-gathered per forward, so checkpoints
        # stay in the canonical layout).  Measured v5e: stem 1.40 -> 1.00
        # ms at B=128 (tools/resnet_mfu_analysis.md).  NHWC only.
        if stem_space_to_depth and data_format != "NHWC":
            from ...framework.errors import InvalidArgumentError

            raise InvalidArgumentError(
                "stem_space_to_depth is an NHWC-layout optimization; use "
                "data_format='NHWC' (the TPU-preferred layout) or drop "
                "the flag")
        self.stem_space_to_depth = bool(stem_space_to_depth)
        self._norm_layer = functools.partial(nn.BatchNorm2D,
                                             data_format=data_format)
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, data_format=data_format)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1,
                                    data_format=data_format)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1),
                                                data_format=data_format)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1, dilate=False):
        norm_layer = self._norm_layer
        downsample = None
        previous_dilation = self.dilation
        if dilate:
            self.dilation *= stride
            stride = 1
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False,
                          data_format=self.data_format),
                norm_layer(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample, 1, 64,
                        previous_dilation, norm_layer,
                        data_format=self.data_format)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, norm_layer=norm_layer,
                                data_format=self.data_format))
        return nn.Sequential(*layers)

    def _stem_s2d(self, x):
        """out[i,j,o] = Σ W[kh,kw,c] X[2i+kh-3, 2j+kw-3, c] (pad 3, stride
        2) re-indexed in 2x2 blocks: kh-3 = 2a+dy → tap a ∈ {-2..1}
        (4-wide kernel, pad (2,1)), block parity dy, packed channel
        dy*2C + dx*C + c."""
        import jax
        import jax.numpy as jnp

        from ... import nn as _nn

        B, H, W, C = x.shape
        if H % 2 or W % 2:
            # odd spatial size: the 2x2 block re-layout doesn't exist —
            # take the standard stem (same result, just slower)
            return self.conv1(x)
        x2 = x.reshape(B, H // 2, 2, W // 2, 2, C).transpose(
            0, 1, 3, 2, 4, 5).reshape(B, H // 2, W // 2, 4 * C)
        # re-gather the canonical OIHW weight as the OIHW 4x4 kernel
        w = jnp.asarray(self.conv1.weight.value)         # [O, C, 7, 7]
        w2 = jnp.zeros((w.shape[0], 4 * C, 4, 4), w.dtype)

        def taps(d):  # (tap_row a+2, kernel row kh) pairs for parity d
            return [(a + 2, 2 * a + d + 3) for a in (-2, -1, 0, 1)
                    if 0 <= 2 * a + d + 3 <= 6]

        for dy in (0, 1):
            for dx in (0, 1):
                lo = dy * 2 * C + dx * C
                for ai, kh in taps(dy):
                    for bi, kw in taps(dx):
                        w2 = w2.at[:, lo:lo + C, ai, bi].set(w[:, :, kh, kw])
        # F.conv2d: gets the AMP mixed-dtype auto-cast and the framework's
        # padding plumbing (asymmetric [top, bottom, left, right])
        return _nn.functional.conv2d(x2, w2, stride=1, padding=[2, 1, 2, 1],
                                     data_format="NHWC")

    def forward(self, x):
        if self.stem_space_to_depth:
            x = self.relu(self.bn1(self._stem_s2d(x)))
        else:
            x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x


def _resnet(arch, Block, depth, pretrained, **kwargs):
    model = ResNet(Block, depth, **kwargs)
    if pretrained:
        from ...framework import serialization

        if not isinstance(pretrained, str):
            raise ValueError(
                "no pretrained-weight download in this environment: pass a "
                "local .pdparams path as `pretrained`")
        model.set_state_dict(serialization.load(pretrained))
    return model


def resnet18(pretrained=False, **kwargs):
    """ResNet-18 (reference surface: vision/models/resnet.py:272)."""
    return _resnet("resnet18", BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    """ResNet-34 (reference surface: vision/models/resnet.py:292)."""
    return _resnet("resnet34", BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    """ResNet-50 (reference surface: vision/models/resnet.py:312) — the
    BASELINE.json flagship CNN (configs 2 and 4)."""
    return _resnet("resnet50", BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    """ResNet-101 (reference surface: vision/models/resnet.py:332)."""
    return _resnet("resnet101", BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    """ResNet-152 (reference surface: vision/models/resnet.py:352)."""
    return _resnet("resnet152", BottleneckBlock, 152, pretrained, **kwargs)

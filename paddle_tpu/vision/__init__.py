"""paddle_tpu.vision — computer-vision models, datasets and transforms.

Capability parity with the reference's ``python/paddle/vision/`` package
(models/resnet.py, datasets/mnist.py, transforms/transforms.py, image.py),
built TPU-first: models are jit-friendly Layer trees whose convolutions
lower to XLA convolutions on the MXU; transforms are host-side numpy
(they run inside DataLoader workers, off the device).
"""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from .image import get_image_backend, image_load, set_image_backend  # noqa: F401

__all__ = ["datasets", "models", "transforms",
           "set_image_backend", "get_image_backend", "image_load"]

"""Mixture-of-experts FFN — GShard-style top-k routing over the
``expert`` mesh axis.

``MoELayer`` is a drop-in replacement for the dense ``ParallelMLP``:
same ``(x) -> y`` signature, same hidden→intermediate→hidden GELU FFN —
but the FFN weights are stacked ``[E, ...]`` per expert and each token is
processed by only the ``top_k`` experts its learned router picks.  The
design keeps every shape static so the layer composes with jit, scan
and the serving engine's closed compile set:

* **Router** — a replicated ``[D, E]`` gate; softmax over experts, then
  ``jax.lax.top_k``.  Training applies multiplicative jitter to the gate
  INPUT (GShard §3.1) drawn from :func:`current_rng_key`, so routing is
  deterministic under a fixed seed and exactly greedy in eval.
* **Capacity** — each expert accepts at most ``C = ceil(k*N*cf/E)``
  tokens (static, from shapes alone).  Slot positions come from a cumsum
  over the one-hot assignment flattened SLOT-MAJOR: every token's 1st
  choice beats any token's 2nd choice, and within a choice rank earlier
  tokens win — the deterministic tie-break the tests pin down.  Overflow
  tokens are dropped for that expert (their combine weight contributes
  nothing; with ``k > 1`` another expert usually still serves them).
* **Dispatch/combine** — one-hot einsums into/out of the ``[E, C, D]``
  capacity buffer, constrained to ``("expert", None, None)`` so GSPMD
  lowers them to all-to-alls over the ``expert`` mesh axis; the layer
  itself never calls a collective (same SPMD idiom as meta_parallel).
* **Expert FFN** — stacked weights named ``expert_*`` (the P506
  contract) with ``("expert", ...)`` partition specs.  On TPU with
  lane-aligned dims the matmuls go through the ``grouped_matmul`` Pallas
  kernel, which skips padding rows in-register; elsewhere the reference
  masked einsum (bit-identical by the kernel's parity test).
* **Aux loss** — the Switch Transformer load-balance loss
  ``E * Σ_e f_e · P_e`` (``f_e`` = fraction of selections, ``P_e`` =
  mean router probability); ≈ 1 when perfectly balanced.  It and the
  per-expert routed/dropped counters ride the trace-scoped
  :mod:`paddle_tpu.moe.stats` collector, keeping ``forward`` signature-
  compatible with the dense MLP.

Dense equivalence (the dryrun gate): with identically initialized
experts, ``top_k=1`` and capacity ≥ tokens, the combine weight is
``p/p == 1.0`` exactly and dispatch/combine are one-hot einsums
(``1.0*x + 0.0*pad``), so forward AND backward are bit-identical to the
dense MLP.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .. import nn
from ..distributed.meta_parallel import constrain
from ..nn import initializer as I
from ..nn.layer_base import Layer, current_rng_key
from . import stats as moe_stats

__all__ = ["MoELayer"]


class MoELayer(Layer):
    """Top-k routed expert FFN; config knobs: ``moe_experts`` (E),
    ``moe_top_k``, ``moe_capacity_factor``, ``moe_jitter`` plus the dense
    MLP's ``hidden_size``/``intermediate_size``/``dropout``."""

    def __init__(self, cfg):
        super().__init__()
        D = cfg.hidden_size
        F = cfg.intermediate_size
        E = int(cfg.moe_experts)
        if E < 1:
            raise ValueError(f"MoELayer needs moe_experts >= 1, got {E}")
        self.num_experts = E
        self.top_k = max(1, min(int(getattr(cfg, "moe_top_k", 2)), E))
        self.capacity_factor = float(getattr(cfg, "moe_capacity_factor",
                                             1.25))
        self.jitter = float(getattr(cfg, "moe_jitter", 0.0))
        # replicated router gate; explicit fans so the stacked expert
        # weights initialize with the same scale a [D, F] dense layer gets
        self.gate = self.create_parameter(
            (D, E), default_initializer=I.XavierNormal())
        self.expert_fc1 = self.create_parameter(
            (E, D, F), default_initializer=I.XavierNormal(fan_in=D,
                                                          fan_out=F))
        self.expert_fc1.partition_spec = ("expert", None, None)
        self.expert_b1 = self.create_parameter((E, F), is_bias=True)
        self.expert_b1.partition_spec = ("expert", None)
        self.expert_fc2 = self.create_parameter(
            (E, F, D), default_initializer=I.XavierNormal(fan_in=F,
                                                          fan_out=D))
        self.expert_fc2.partition_spec = ("expert", None, None)
        self.expert_b2 = self.create_parameter((E, D), is_bias=True)
        self.expert_b2.partition_spec = ("expert", None)
        self.act = nn.GELU()
        self.drop = nn.Dropout(cfg.dropout)

    def capacity(self, num_tokens: int) -> int:
        """Static per-expert slot count for ``num_tokens`` routed rows."""
        return max(1, math.ceil(self.top_k * num_tokens *
                                self.capacity_factor / self.num_experts))

    def _expert_ffn(self, xe, group_sizes):
        """[E, C, D] -> [E, C, D]; rows past group_sizes[e] may hold
        garbage (FFN of a zero row is the bias path) — combine's one-hot
        weights never read them."""
        w1, w2 = self.expert_fc1.value, self.expert_fc2.value
        b1, b2 = self.expert_b1.value, self.expert_b2.value
        if self._use_kernel(xe):
            from ..ops.grouped_matmul import grouped_matmul

            h = grouped_matmul(xe, w1, group_sizes) + b1[:, None, :]
            h = self.act(h)
            return grouped_matmul(h, w2, group_sizes) + b2[:, None, :]
        h = jnp.einsum("ecd,edf->ecf", xe, w1) + b1[:, None, :]
        h = self.act(h)
        return jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :]

    def _use_kernel(self, xe) -> bool:
        from ..ops.autotune import fused_epilogues_eligible

        D = xe.shape[-1]
        F = self.expert_fc1.value.shape[-1]
        return (fused_epilogues_eligible(D)
                and fused_epilogues_eligible(F))

    def forward(self, x):
        x = jnp.asarray(x)
        lead = x.shape[:-1]
        D = x.shape[-1]
        E, k = self.num_experts, self.top_k
        xf = x.reshape(-1, D)
        N = xf.shape[0]
        C = self.capacity(N)

        gate_in = xf
        if self.training and self.jitter > 0.0:
            eps = self.jitter
            gate_in = xf * jax.random.uniform(
                current_rng_key(), xf.shape, dtype=xf.dtype,
                minval=1.0 - eps, maxval=1.0 + eps)
        logits = gate_in @ self.gate.value
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        top_p, top_e = jax.lax.top_k(probs, k)          # [N, k]
        # normalized combine weights.  For top-1 that is p/p: value 1.0
        # and derivative exactly zero, so spell it as the constant — the
        # autodiff of the quotient leaves last-ulp noise that would break
        # the dense-parity bit-identity; the router trains through the
        # balance loss (k == 1) or the relative weights (k > 1)
        if k == 1:
            combine_w = jnp.ones_like(top_p)
        else:
            combine_w = top_p / top_p.sum(-1, keepdims=True)

        onehot = jax.nn.one_hot(top_e, E, dtype=jnp.int32)   # [N, k, E]
        # position-in-expert: cumsum in slot-major-then-token order, so
        # 1st choices beat 2nd choices and earlier tokens beat later ones
        flat = onehot.transpose(1, 0, 2).reshape(k * N, E)
        pos_flat = jnp.cumsum(flat, axis=0) - flat
        pos = pos_flat.reshape(k, N, E).transpose(1, 0, 2)   # [N, k, E]
        slot = (pos * onehot).sum(-1)                        # [N, k]
        kept = (slot < C) & (onehot.sum(-1) > 0)
        # one_hot of a negative index is all-zero: dropped slots vanish
        cap_oh = jax.nn.one_hot(jnp.where(kept, slot, -1), C,
                                dtype=jnp.float32)           # [N, k, C]
        oh_f = onehot.astype(jnp.float32)
        disp = jnp.einsum("nke,nkc->nec", oh_f, cap_oh)      # [N, E, C]
        comb = jnp.einsum("nke,nkc,nk->nec", oh_f, cap_oh,
                          combine_w.astype(jnp.float32))

        xe = jnp.einsum("nec,nd->ecd", disp.astype(xf.dtype), xf)
        xe = constrain(xe, "expert", None, None)
        selected = onehot.sum((0, 1))                        # [E] i32
        routed = jnp.minimum(selected, C).astype(jnp.int32)
        ye = self._expert_ffn(xe, routed)
        ye = constrain(ye, "expert", None, None)
        y = jnp.einsum("nec,ecd->nd", comb.astype(ye.dtype), ye)

        # Switch load-balance loss: E * sum_e f_e * P_e  (≈ 1 balanced)
        f = selected.astype(jnp.float32) / float(N * k)
        P = probs.mean(0)
        aux = float(E) * jnp.sum(f * P)
        moe_stats.record(aux, routed, (selected - routed).astype(jnp.int32))

        return self.drop(y.reshape(*lead, D))

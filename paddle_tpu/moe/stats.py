"""Trace-scoped MoE side-channel — aux losses and routing counters.

``MoELayer.forward`` must stay signature-compatible with the dense
``ParallelMLP`` (``(x) -> y``), so its auxiliary outputs — the
load-balance loss every MoE block contributes and the per-expert
routed/dropped counters the serving loop publishes — cannot ride the
return value.  They ride this collector instead: whoever owns the trace
(``GPTForCausalLM.forward`` for training, the serving engine's jitted
step bodies for decode) opens :class:`collect` around the model call and
reads the recorded TRACED values back inside the same trace.  Nothing
here crosses a jit boundary on its own; the collector is just a
trace-time mailbox.

The stack is thread-local: the serving decode loop traces in its own
thread while a training step traces in the main thread, and neither may
see the other's entries.
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax.numpy as jnp

__all__ = ["MoEStats", "collect", "record", "active"]

_local = threading.local()


def _stack() -> List["MoEStats"]:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


class MoEStats:
    """One trace's MoE entries: per-layer ``(aux, routed [E], dropped
    [E])`` triples, all traced arrays."""

    def __init__(self):
        self.entries: List[tuple] = []

    def add(self, aux, routed, dropped):
        self.entries.append((aux, routed, dropped))

    def total_aux(self):
        """Sum of the recorded load-balance losses (traced scalar), or
        ``None`` when no MoE layer ran."""
        if not self.entries:
            return None
        out = self.entries[0][0]
        for aux, _, _ in self.entries[1:]:
            out = out + aux
        return out

    def counts(self, num_experts: int):
        """``[2, E]`` int32 — row 0 routed tokens per expert, row 1
        dropped (capacity-overflow) tokens, summed over layers.  Zeros
        when no MoE layer ran."""
        routed = jnp.zeros((num_experts,), jnp.int32)
        dropped = jnp.zeros((num_experts,), jnp.int32)
        for _, r, d in self.entries:
            routed = routed + r
            dropped = dropped + d
        return jnp.stack([routed, dropped])


class collect:
    """``with collect() as ms:`` — capture MoE records from the model
    calls inside the block (re-entrant; inner collectors shadow)."""

    def __enter__(self) -> MoEStats:
        st = MoEStats()
        _stack().append(st)
        return st

    def __exit__(self, *exc):
        _stack().pop()
        return False


def record(aux, routed, dropped):
    """Called by ``MoELayer.forward``; a no-op when nobody collects."""
    st = _stack()
    if st:
        st[-1].add(aux, routed, dropped)


def active() -> bool:
    return bool(_stack())

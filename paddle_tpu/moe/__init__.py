"""paddle_tpu.moe — expert-parallel mixture-of-experts layers.

* ``MoELayer`` — GShard-style top-k routed expert FFN, a drop-in for the
  dense ``ParallelMLP`` behind ``GPTConfig.moe_experts`` (layer.py);
* ``stats`` — the trace-scoped collector carrying each layer's load-
  balance loss and routed/dropped counters to whoever owns the trace
  (stats.py).

Expert weights shard over the ``expert`` mesh axis
(``distributed.mesh.AXIS_ORDER``); dispatch/combine are static-shape
capacity-bucketed one-hot einsums that GSPMD lowers to all-to-alls.
"""
from . import stats  # noqa: F401
from .layer import MoELayer  # noqa: F401

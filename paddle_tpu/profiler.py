"""Profiler — device traces + host-side event timing.

Parity: paddle/fluid/platform/profiler.h:40-212 (RecordEvent, Enable/
DisableProfiler, the event table printed by PrintProfiler) and
python/paddle/fluid/profiler.py (profiler context manager,
start_profiler/stop_profiler/reset_profiler).

TPU-native design: the *device* timeline comes from the XLA profiler —
``start_profiler(log_dir)`` wraps ``jax.profiler.start_trace`` and writes a
TensorBoard/perfetto-loadable trace of every compiled computation, transfer
and ICI collective (far richer than the reference's per-op CUDA event
pairs).  The *host* table the reference prints is kept too: ``RecordEvent``
annotates the device trace AND accumulates wall-clock stats, and
``stop_profiler``/``summary`` prints the familiar
name/calls/total/avg/min/max table.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

import jax

__all__ = [
    "RecordEvent",
    "start_profiler",
    "stop_profiler",
    "reset_profiler",
    "profiler",
    "profiling_active",
    "record_span",
    "dropped_spans",
    "summary",
    "export_chrome_tracing",
    "register_summary_section",
]

_lock = threading.Lock()
_events: Dict[str, dict] = {}
_spans: list = []  # (name, tid, start_us, dur_us, cat, args) while profiling
_SPAN_CAP = 200_000  # keep the host-side buffer bounded
_dropped_spans = 0  # spans past the cap — counted, not silently lost
_trace_dir: Optional[str] = None
_started = False
_sections: list = []  # (render_fn, on_reset) extra summary() sections


def register_summary_section(render_fn, on_reset=None) -> None:
    """Let a subsystem append its own block to ``summary()``.

    ``render_fn() -> str`` runs at summary time; an empty string means
    "nothing to report" and the section is skipped (so ``summary()``
    still returns ``""`` when there is nothing at all to show).
    ``on_reset`` (optional) runs inside ``reset_profiler()`` so the
    subsystem can snapshot its counters — sections report activity since
    the last reset, matching the host-event table's lifecycle.  Used by
    ``ops.autotune`` for the kernel-tuning cache statistics."""
    with _lock:
        _sections.append((render_fn, on_reset))


class RecordEvent:
    """Annotate a region: shows up named in the device trace and in the
    host event table.  Context manager or decorator.

    Parity: platform/profiler.h:121 RecordEvent.
    """

    def __init__(self, name: str):
        self.name = name
        self._ann = None
        self._t0 = 0.0

    def __enter__(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        global _dropped_spans
        t1 = time.perf_counter()
        dt = (t1 - self._t0) * 1e3  # ms
        self._ann.__exit__(*exc)
        with _lock:
            e = _events.setdefault(
                self.name,
                {"calls": 0, "total": 0.0, "min": float("inf"), "max": 0.0})
            e["calls"] += 1
            e["total"] += dt
            e["min"] = min(e["min"], dt)
            e["max"] = max(e["max"], dt)
            if _started:
                if len(_spans) < _SPAN_CAP:
                    _spans.append((self.name, threading.get_ident(),
                                   self._t0 * 1e6, dt * 1e3, "host", None))
                else:
                    _dropped_spans += 1
        return False

    def __call__(self, fn):
        def wrapped(*a, **k):
            with RecordEvent(self.name):
                return fn(*a, **k)

        return wrapped


def start_profiler(log_dir: Optional[str] = None, state: str = "All",
                   tracer_option: str = "Default"):
    """Begin profiling.  ``log_dir`` set → also capture the XLA device trace
    there (view in TensorBoard's profile plugin / Perfetto).

    Parity: fluid/profiler.py start_profiler (state/tracer_option accepted
    for signature compatibility; the XLA trace always covers both CPU and
    device activity).
    """
    global _trace_dir, _started
    if _started:
        raise RuntimeError(
            "profiler already running — call stop_profiler() first")
    reset_profiler()
    if log_dir is not None:
        jax.profiler.start_trace(log_dir)
        _trace_dir = log_dir
    _started = True


def stop_profiler(sorted_key: Optional[str] = "total",
                  profile_path: Optional[str] = None) -> str:
    """End profiling; returns (and prints) the host event table.  With a
    ``log_dir`` given at start, finalizes the device trace.

    Parity: fluid/profiler.py stop_profiler (sorted_key: one of
    calls/total/max/min/ave)."""
    global _trace_dir, _started
    if not _started:
        return ""  # stop without start: nothing to finalize
    if _trace_dir is not None:
        jax.profiler.stop_trace()
        _trace_dir = None
    _started = False
    table = summary(sorted_key=sorted_key)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(table)
    if table:
        print(table)
    return table


def profiling_active() -> bool:
    """True between start_profiler and stop_profiler — span producers
    outside this module (the serving batcher) check it before paying the
    span-assembly cost."""
    return _started


def record_span(name: str, start_s: float, dur_ms: float, *,
                tid: Optional[int] = None, cat: str = "host",
                args: Optional[dict] = None) -> bool:
    """Record an externally-timed span (``start_s`` on the perf_counter /
    monotonic clock base) into the chrome-trace buffer.  Used by the
    serving layer for per-request queue/execute spans.  No-op unless the
    profiler is running; respects (and counts overflow past) the span
    cap.  Returns whether the span was kept."""
    global _dropped_spans
    if not _started:
        return False
    with _lock:
        if not _started:
            return False
        if len(_spans) >= _SPAN_CAP:
            _dropped_spans += 1
            return False
        _spans.append((name, tid if tid is not None
                       else threading.get_ident(),
                       start_s * 1e6, dur_ms * 1e3, cat, args))
        return True


def dropped_spans() -> int:
    """Spans lost past ``_SPAN_CAP`` since the last reset."""
    with _lock:
        return _dropped_spans


def reset_profiler():
    """Parity: fluid/profiler.py reset_profiler."""
    global _dropped_spans
    with _lock:
        _events.clear()
        _spans.clear()
        _dropped_spans = 0
        hooks = [h for _, h in _sections if h is not None]
    for hook in hooks:
        hook()


def export_chrome_tracing(path: str) -> int:
    """Write the recorded host spans as a chrome://tracing /
    ui.perfetto.dev JSON file (capability of the reference's
    tools/timeline.py, which converted profiler protos the same way).
    Returns the number of spans written.  Device-side timelines come
    from the XLA trace (``start_profiler(log_dir=...)``) — this covers
    the host RecordEvent annotations."""
    import json

    with _lock:
        spans = list(_spans)
        dropped = _dropped_spans
    events = []
    for name, tid, ts_us, dur_us, cat, args in spans:
        ev = {"name": name, "ph": "X", "pid": 0, "tid": tid,
              "ts": round(ts_us, 3), "dur": round(dur_us, 3),
              "cat": cat}
        if args:
            ev["args"] = args
        events.append(ev)
    # request-tracing spans share the monotonic base (perf_counter and
    # monotonic are both CLOCK_MONOTONIC on Linux), so they land on the
    # same timeline as the RecordEvent spans
    from .observability import tracing as _tracing

    tr = _tracing._active
    if tr is not None:
        events.extend(tr.chrome_events())
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms",
                   "otherData": {"dropped_spans": dropped}}, f)
    return len(events)


def summary(sorted_key: Optional[str] = "total") -> str:
    """The reference's PrintProfiler table (profiler.cc) from host events,
    followed by any registered subsystem sections (see
    ``register_summary_section``)."""
    with _lock:
        rows = [
            (name, e["calls"], e["total"], e["total"] / e["calls"],
             e["min"], e["max"])
            for name, e in _events.items()
        ]
        sections = [fn for fn, _ in _sections]
        dropped = _dropped_spans
    extra = [s for s in (fn() for fn in sections) if s]
    if dropped:
        extra.append(f"[profiler] {dropped} span(s) dropped past the "
                     f"{_SPAN_CAP} span cap — the chrome trace is "
                     f"truncated; profile a shorter window")
    if not rows:
        return "\n\n".join(extra) if extra else ""
    key_idx = {"calls": 1, "total": 2, "ave": 3, "min": 4, "max": 5}.get(
        sorted_key or "total", 2)
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    grand = sum(r[2] for r in rows) or 1.0
    w = max(len(r[0]) for r in rows) + 2
    lines = [
        f"{'Event':<{w}}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
        f"{'Min(ms)':>10}{'Max(ms)':>10}{'Ratio':>8}"
    ]
    for name, calls, total, avg, mn, mx in rows:
        lines.append(
            f"{name:<{w}}{calls:>8}{total:>12.3f}{avg:>10.3f}"
            f"{mn:>10.3f}{mx:>10.3f}{total / grand:>8.2%}")
    table = "\n".join(lines)
    return "\n\n".join([table] + extra) if extra else table


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = "total",
             profile_path: Optional[str] = None,
             log_dir: Optional[str] = None):
    """``with profiler(...):`` — parity with fluid.profiler.profiler.

    The reference's ``state`` chose CPU vs GPU event capture; the XLA trace
    captures both, so it is accepted and ignored.
    """
    start_profiler(log_dir=log_dir, state=state)
    try:
        yield
    finally:
        stop_profiler(sorted_key=sorted_key, profile_path=profile_path)
